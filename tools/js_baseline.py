"""Measure the ACTUAL JS backend baseline (round-5 VERDICT "What's
missing" #1): run BASELINE.md configs 1-3 through the reference backend's
``applyChanges`` under a real JS engine and print the measured rates, so
BASELINE.md can replace its hand-waved 5-10x V8 factor with a number.

The harness is engine-agnostic: it tries, in order,
``py_mini_racer`` (embedded V8), ``pythonmonkey`` (SpiderMonkey),
``quickjs``, then a ``node`` binary on PATH. The reference sources are
located via ``$AM_REFERENCE_JS`` (a directory holding ``backend/*.js`` and
``common.js``/``src/common.js``) or the conventional ``/root/reference``
mount. Change batches are generated with THIS repo's columnar encoder —
binary changes are the wire format, identical for every backend — and
shipped into JS as base64.

When no engine or no sources exist (this image has neither: no Node, no JS
engine wheels, and no network to fetch one — ``pip download py-mini-racer``
returns "no matching distribution"), the harness prints a structured
``{"status": "unavailable", ...}`` JSON line and exits 3, so CI and
BASELINE.md record the gate honestly instead of a silent skip. The moment
an engine lands in the image, ``python tools/js_baseline.py`` produces the
measured vs-JS ratio with no code changes.

Usage:
    python tools/js_baseline.py            # all configs, JSON per line
    AM_JS_DOCS=100 python tools/js_baseline.py   # smaller config 3
"""

import base64
import json
import os
import shutil
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Engine discovery
# ---------------------------------------------------------------------------

def _try_mini_racer():
    try:
        from py_mini_racer import MiniRacer
    except ImportError:
        return None

    class V8:
        name = 'py_mini_racer (V8)'

        def __init__(self):
            self.ctx = MiniRacer()

        def eval(self, src):
            return self.ctx.eval(src)

    return V8()


def _try_pythonmonkey():
    try:
        import pythonmonkey
    except ImportError:
        return None

    class SM:
        name = 'pythonmonkey (SpiderMonkey)'

        def eval(self, src):
            return pythonmonkey.eval(src)

    return SM()


def _try_quickjs():
    try:
        import quickjs
    except ImportError:
        return None

    class QJS:
        name = 'quickjs'

        def __init__(self):
            self.ctx = quickjs.Context()

        def eval(self, src):
            return self.ctx.eval(src)

    return QJS()


def _try_node():
    node = shutil.which('node')
    if node is None:
        return None

    class Node:
        name = f'node ({node})'

        def eval(self, src):
            proc = subprocess.run([node, '-e', src + '\n'],
                                  capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr[-2000:])
            return proc.stdout

    return Node()


def find_engine():
    for probe in (_try_mini_racer, _try_pythonmonkey, _try_quickjs,
                  _try_node):
        engine = probe()
        if engine is not None:
            return engine
    return None


def find_reference():
    """Directory with the reference JS backend sources, or None."""
    for root in (os.environ.get('AM_REFERENCE_JS'), '/root/reference'):
        if root and os.path.isdir(os.path.join(root, 'backend')):
            return root
    return None


# ---------------------------------------------------------------------------
# JS bundle: reference backend + a timing driver, one self-contained script
# ---------------------------------------------------------------------------

def build_bundle(ref_root, payload_b64, reps):
    """Wrap the reference backend sources and a timing driver into one
    script. The reference uses CommonJS requires; a tiny module shim keeps
    the sources verbatim (do-not-modify ground truth)."""
    backend_dir = os.path.join(ref_root, 'backend')
    sources = {}
    for name in sorted(os.listdir(backend_dir)):
        if name.endswith('.js'):
            with open(os.path.join(backend_dir, name)) as f:
                sources[f'./{name[:-3]}'] = f.read()
    for rel in ('src/common.js', 'common.js'):
        path = os.path.join(ref_root, rel)
        if os.path.exists(path):
            with open(path) as f:
                sources['../src/common'] = sources['./common'] = f.read()
            break
    modules = json.dumps(sources)
    return f"""
'use strict';
const __SOURCES = {modules};
const __CACHE = {{}};
function require(name) {{
  name = name.replace(/\\.js$/, '');
  const key = __SOURCES[name] !== undefined ? name
      : name.replace(/^\\.\\.\\/src\\//, '../src/');
  if (__SOURCES[key] === undefined) throw new Error('no module ' + name);
  if (!__CACHE[key]) {{
    const module = {{exports: {{}}}};
    __CACHE[key] = module.exports;
    new Function('module', 'exports', 'require', __SOURCES[key])(
        module, module.exports, require);
    __CACHE[key] = module.exports;
  }}
  return __CACHE[key];
}}
const Backend = require('./backend');
const __payload = JSON.parse(
    typeof atob === 'function' ? atob('{payload_b64}')
    : Buffer.from('{payload_b64}', 'base64').toString());
function b64bytes(s) {{
  if (typeof Buffer !== 'undefined') return new Uint8Array(Buffer.from(s, 'base64'));
  const raw = atob(s), out = new Uint8Array(raw.length);
  for (let i = 0; i < raw.length; i++) out[i] = raw.charCodeAt(i);
  return out;
}}
const results = {{}};
for (const [config, docs] of Object.entries(__payload)) {{
  const batches = docs.map(doc => doc.map(b64bytes));
  let best = Infinity, applied = 0;
  for (let rep = 0; rep < {reps}; rep++) {{
    const t0 = Date.now();
    applied = 0;
    for (const changes of batches) {{
      let state = Backend.init();
      [state] = Backend.applyChanges(state, changes);
      applied += changes.length;
    }}
    best = Math.min(best, (Date.now() - t0) / 1000);
  }}
  results[config] = {{changes: applied, seconds: best,
                      changes_per_sec: applied / best}};
}}
const __out = JSON.stringify(results);
if (typeof console !== 'undefined' && console.log) console.log(__out);
__out;
"""


# ---------------------------------------------------------------------------
# Workload generation (BASELINE.md configs 1-3, this repo's encoder)
# ---------------------------------------------------------------------------

def gen_config1():
    """2-actor map doc, 1k concurrent key sets."""
    from automerge_tpu.columnar import encode_change
    actors = ['aa' * 16, 'bb' * 16]
    changes = []
    for i in range(1000):
        a = i % 2
        changes.append(encode_change({
            'actor': actors[a], 'seq': i // 2 + 1, 'startOp': i + 1,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': f'k{i % 64}',
                     'value': i, 'datatype': 'int', 'pred': []}]}))
    return [changes]


def gen_config2(n_chars=10000):
    """Text editing trace: 3 actors, insert-heavy with deletes."""
    from automerge_tpu.columnar import encode_change, decode_change_meta
    actors = ['aa' * 16, 'bb' * 16, 'cc' * 16]
    changes, heads, seqs = [], [], [0, 0, 0]
    make = encode_change({
        'actor': actors[0], 'seq': 1, 'startOp': 1, 'time': 0,
        'message': '', 'deps': [],
        'ops': [{'action': 'makeText', 'obj': '_root', 'key': 'text',
                 'pred': []}]})
    heads = [decode_change_meta(make, True)['hash']]
    changes.append(make)
    seqs[0] = 1
    text_id = f'1@{actors[0]}'
    op = 2
    prev = '_head'
    for i in range(n_chars):
        a = i % 3
        seqs[a] += 1
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': op, 'time': 0,
            'message': '', 'deps': heads,
            'ops': [{'action': 'set', 'obj': text_id, 'elemId': prev,
                     'insert': True, 'value': chr(97 + i % 26),
                     'pred': []}]})
        prev = f'{op}@{actors[a]}'
        op += 1
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    return [changes]


def gen_config3(n_docs=None, changes_per_doc=100):
    """1k-doc batch x 100 changes each, map + Counter ops."""
    from automerge_tpu.columnar import encode_change, decode_change_meta
    n_docs = n_docs or int(os.environ.get('AM_JS_DOCS', 1000))
    actors = ['aa' * 16, 'bb' * 16]
    changes, heads, seqs = [], [], [0, 0]
    for c in range(changes_per_doc):
        a = c % 2
        seqs[a] += 1
        if c % 5 == 4:
            op = {'action': 'inc', 'obj': '_root', 'key': 'counter',
                  'value': 1, 'pred': [f'1@{actors[0]}']}
        elif c == 0:
            op = {'action': 'set', 'obj': '_root', 'key': 'counter',
                  'value': 0, 'datatype': 'counter', 'pred': []}
        else:
            op = {'action': 'set', 'obj': '_root', 'key': f'k{c % 32}',
                  'value': c, 'datatype': 'int', 'pred': []}
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': c + 1,
            'time': 0, 'message': '', 'deps': heads, 'ops': [op]})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    return [list(changes) for _ in range(n_docs)]


CONFIGS = {'config1': gen_config1, 'config2': gen_config2,
           'config3': gen_config3}


def main():
    engine = find_engine()
    ref_root = find_reference()
    if engine is None or ref_root is None:
        print(json.dumps({
            'status': 'unavailable',
            'engine': engine.name if engine else None,
            'reference': ref_root,
            'reason': 'no JS engine importable/installed'
                      if engine is None else 'reference JS sources not '
                      'mounted (set AM_REFERENCE_JS)',
            'tried': ['py_mini_racer', 'pythonmonkey', 'quickjs', 'node'],
        }))
        sys.exit(3)

    payload = {}
    for name, gen in CONFIGS.items():
        docs = gen()
        payload[name] = [[base64.b64encode(bytes(ch)).decode()
                          for ch in doc] for doc in docs]
    reps = int(os.environ.get('AM_JS_REPS', 3))
    bundle = build_bundle(
        ref_root,
        base64.b64encode(json.dumps(payload).encode()).decode(), reps)

    start = time.time()
    raw = engine.eval(bundle)
    if isinstance(raw, str):
        raw = raw.strip().splitlines()[-1]
    results = raw if isinstance(raw, dict) else json.loads(raw)
    print(json.dumps({
        'status': 'ok', 'engine': engine.name,
        'wall_seconds': round(time.time() - start, 1),
        'results': results,
    }))


if __name__ == '__main__':
    main()
