"""Replay the fuzz corpus against the SANITIZED native codec.

The normal fuzz net (tools/fuzz_wire.py) enforces the typed-error
envelope against the -O3 codec; a heap overread that happens to land in
mapped memory sails right through it. This tool is the memory-safety
half of that contract: build hostile mutants from the same corpus and
feed them to every native entry point with the codec compiled at
`-fsanitize=address,undefined` (tools/build_native.sh --sanitize). Any
out-of-bounds access, use-after-free, or UB aborts the child process —
the parent turns that into a nonzero exit.

Split into two processes because the sanitized .so and the fuzz corpus
have incompatible needs:

- the PARENT builds the corpus via tools/fuzz_wire.py, which imports the
  full stack (jax included) — loading an ASan-instrumented .so into that
  process would need ASan to interpose malloc before jax/XLA start
  allocating, and the host python is not ASan-linked;
- the CHILD (`--child`) imports ONLY `automerge_tpu.native` (jax-free,
  ~0.1s) with `AUTOMERGE_TPU_NATIVE_SO` pointing at the sanitized build
  and `LD_PRELOAD` carrying libasan/libubsan, so the sanitizer runtime
  is in place before the codec loads.

The child catches Python-level exceptions (typed rejections are the
EXPECTED outcome for mutants; the envelope itself is fuzz_wire's job at
the normal build) — only a sanitizer abort, a crash, or a corpus
shortfall fails the replay.

Usage:
  tools/build_native.sh --sanitize=address,undefined
  python tools/native_sanitize_replay.py [--seeds N] [--cases N] [--so PATH]
"""

import argparse
import os
import pickle
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SAN_SUFFIX = 'address-undefined'


def default_san_so():
    tag = sys.implementation.cache_tag
    return os.path.join(REPO, 'automerge_tpu', 'native',
                        f'_codec_{tag}_san.{SAN_SUFFIX}.so')


def sanitizer_preload():
    """The libasan/libubsan runtime paths for LD_PRELOAD, or None when
    the toolchain does not ship them (then there is nothing to replay
    under and callers should skip, not fail)."""
    libs = []
    for name in ('libasan.so', 'libubsan.so'):
        try:
            out = subprocess.run(['gcc', f'-print-file-name={name}'],
                                 capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        path = out.stdout.strip()
        # gcc echoes the bare name back when it has no such library
        if not path or path == name or not os.path.exists(path):
            return None
        libs.append(os.path.realpath(path))
    return ':'.join(libs)


# regression pins: payloads that once tripped the sanitizer stay in the
# replay forever, whatever the seeded mutator happens to generate
HANDCRAFTED = [
    # 10-byte SLEB whose final byte lands at shift 63: `42 << 63` was UB
    # in read_sleb when it assembled into a signed int64 (UBSan, found
    # by this replay; codec.cpp now assembles unsigned)
    ('handcrafted:sleb-shift63', bytes([0xaa] * 9 + [0x2a])),
    # INT64_MIN as a literal-run count (-count negation guard)
    ('handcrafted:sleb-int64min', bytes([0x80] * 9 + [0x01])),
    # ULEB longer than 64 bits (shift >= 64 error path)
    ('handcrafted:uleb-overlong', bytes([0xff] * 10 + [0x01])),
]


def build_cases(n_seeds, n_cases):
    """(name, payload) replay cases: every pristine corpus artifact plus
    seeded mutants — the pristine items drive the success paths (RLE
    runs, deflated columns, multi-change docs) under the sanitizer, the
    mutants drive the bounds checks."""
    import random

    from tools import fuzz_wire  # heavy import (full stack), parent-only

    corpus = fuzz_wire.build_corpus()
    flat = [(kind, item) for kind, items in corpus.items()
            for item in items]
    cases = list(HANDCRAFTED)
    cases += [(f'corpus:{kind}', bytes(item)) for kind, item in flat]
    for seed in range(n_seeds):
        rng = random.Random(seed)
        for case in range(n_cases):
            kind, base = flat[rng.randrange(len(flat))]
            cases.append((f'mutant:{kind}:{seed}:{case}',
                          fuzz_wire.mutate(rng, base)))
    return cases


def child_main(cases_path):
    """Runs inside the sanitized environment. Keep this jax-free."""
    from automerge_tpu import native

    so = os.environ.get('AUTOMERGE_TPU_NATIVE_SO')
    if not so:
        print('child: AUTOMERGE_TPU_NATIVE_SO is not set', file=sys.stderr)
        return 2
    if not native.available():
        print(f'child: sanitized codec failed to load: {so}',
              file=sys.stderr)
        return 2

    with open(cases_path, 'rb') as fh:
        cases = pickle.load(fh)

    # every native entry point that eats untrusted bytes; max_size on
    # inflate is capped so a mutant length header cannot OOM the replay
    targets = [
        ('sha256', native.sha256),
        ('sha256_batch', lambda m: native.sha256_batch([m, m])),
        ('deflate', native.deflate_raw),
        ('inflate', lambda m: native.inflate_raw(m, max_size=1 << 20)),
        ('rle', native.decode_rle_column),
        ('rle_signed', lambda m: native.decode_rle_column(m, signed=True)),
        ('delta', native.decode_delta_column),
        ('boolean', native.decode_boolean_column),
        ('ingest', lambda m: native.ingest_changes(
            [m], None, with_meta=True, with_seq=True)),
        ('parse_documents', lambda m: native.parse_documents([m])),
        ('extract_changes', lambda m: native.extract_changes([m])),
        ('build_document', lambda m: native.build_document([m], [])),
    ]

    ran = 0
    outcomes = {}
    for _name, payload in cases:
        for tname, fn in targets:
            try:
                fn(payload)
                verdict = 'ok'
            except Exception as exc:  # noqa: BLE001 — envelope is fuzz_wire's job
                verdict = type(exc).__name__
            key = (tname, verdict)
            outcomes[key] = outcomes.get(key, 0) + 1
            ran += 1

    for (tname, verdict), count in sorted(outcomes.items()):
        print(f'child: {tname:16s} {verdict:24s} x{count}')
    print(f'child: replayed {ran} (case, target) pairs over '
          f'{len(cases)} payloads, sanitizer quiet')
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--so', default=None,
                    help='sanitized .so (default: the build_native.sh '
                         '--sanitize artifact for this interpreter)')
    ap.add_argument('--seeds', type=int,
                    default=int(os.environ.get('FUZZ_SEEDS', '5')))
    ap.add_argument('--cases', type=int,
                    default=int(os.environ.get('FUZZ_CASES', '40')))
    ap.add_argument('--child', metavar='CASES_PKL', default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return child_main(args.child)

    so = os.path.abspath(args.so or default_san_so())
    if not os.path.exists(so):
        print(f'sanitized codec not built: {so}\n'
              f'build it with: tools/build_native.sh '
              f'--sanitize=address,undefined', file=sys.stderr)
        return 2
    preload = sanitizer_preload()
    if preload is None:
        print('toolchain has no libasan/libubsan runtime; nothing to '
              'replay under', file=sys.stderr)
        return 2

    cases = build_cases(args.seeds, args.cases)
    env = dict(os.environ)
    env['AUTOMERGE_TPU_NATIVE_SO'] = so
    env['LD_PRELOAD'] = preload
    # the replay python is not ASan-linked, so interceptors see "leaks"
    # from the interpreter itself; halt_on_error stays on for real bugs
    env['ASAN_OPTIONS'] = 'detect_leaks=0:abort_on_error=1'
    env['UBSAN_OPTIONS'] = 'halt_on_error=1:print_stacktrace=1'

    with tempfile.TemporaryDirectory(prefix='am_san_replay_') as tmp:
        cases_path = os.path.join(tmp, 'cases.pkl')
        with open(cases_path, 'wb') as fh:
            pickle.dump(cases, fh)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             '--child', cases_path],
            env=env, cwd=REPO, timeout=1800)
    if proc.returncode != 0:
        print(f'SANITIZER REPLAY FAILED (child rc={proc.returncode}): '
              f'{len(cases)} payloads against {so}', file=sys.stderr)
        return 1
    print(f'sanitize replay clean: {len(cases)} payloads '
          f'({args.seeds} seeds x {args.cases} cases + corpus) '
          f'against {os.path.basename(so)}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
