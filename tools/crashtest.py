"""Crash-injection harness for the durability layer (fleet/durability.py).

Runs a scripted journaled workload (N docs, R rounds, a checkpoint in the
middle), then injects faults into a COPY of the durability directory and
recovers it, proving the recovery contract for every injected crash
point:

- **kill matrix** — truncate the journal at seeded random byte offsets
  (the on-disk effect of a process killed mid-write: the suffix was
  simply never written, possibly splitting the final frame);
- **torn final frame** — cut mid-way through the journal's last frame;
- **bit-rot matrix** — flip one seeded bit inside a journal CHANGE frame
  (header, payload, or CRC bytes) and inside a snapshot DOC frame;
- **checkpoint-crash matrix** — die at each labeled step of the
  checkpoint protocol (temp snapshot written, snapshot renamed, journal
  rotated, manifest flipped) via the ``DurableFleet._fault`` hook.

For every fault the recovered fleet must satisfy the byte-identical
expectation: each unaffected doc's ``save()`` equals the pre-crash
checkpoint + replayed-suffix state, and the (at most one) victim doc
lands exactly on its longest surviving change prefix — with torn tails
truncated and rotted records reported typed (report + health counters),
never as an untyped escape or a fleet-wide failure.

The expectation model is independent of the recovery code path: it
parses the PRE-fault journal for frame boundaries, computes the
surviving record set implied by the fault (complete frames below a
truncation offset; everything except the damaged frame and the victim's
subsequent records for rot), and replays that set through a fresh CLEAN
fleet.

Modes cover the replay matrix: the LWW-grid fleet through the turbo path
(``lww``), the same grid through the host-exact mirror path
(``lww-mirror``), and the exact-device register engine (``exact``).

Dose scales like tools/fuzz_wire.py: CRASH_SEEDS x CRASH_POINTS
(env-overridable); tests/test_durability.py runs a seeded smoke dose in
tier-1, ``python tools/crashtest.py`` the full matrix standalone.
"""

import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

from automerge_tpu.columnar import encode_change                 # noqa: E402
from automerge_tpu.errors import AutomergeError                  # noqa: E402
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet import durability as D                  # noqa: E402
from automerge_tpu.fleet.backend import DocFleet                 # noqa: E402
from automerge_tpu.fleet.durability import DurableFleet          # noqa: E402

MODES = {
    'lww': dict(exact_device=False, mirror=False),
    'lww-mirror': dict(exact_device=False, mirror=True),
    'exact': dict(exact_device=True, mirror=False),
}


class _SimulatedCrash(Exception):
    pass


class _CrashingFleet(DurableFleet):
    """DurableFleet that dies at a chosen checkpoint-protocol step."""

    crash_at = None

    def _fault(self, point):
        if point == self.crash_at:
            raise _SimulatedCrash(point)


# ---------------------------------------------------------------------------
# scripted workload
# ---------------------------------------------------------------------------


class _DocScript:
    """Deterministic single-actor linear change chain for one doc."""

    def __init__(self, idx):
        self.actor = f'{idx:02x}' * 16
        self.seq = 0
        self.start_op = 1

    def make(self, heads, rng):
        self.seq += 1
        n_ops = 1 + (rng.random() < 0.3)
        ops = [{'action': 'set', 'obj': '_root',
                'key': f'k{rng.randrange(8)}',
                'value': rng.randrange(1000), 'datatype': 'int',
                'pred': []} for _ in range(n_ops)]
        buf = encode_change({
            'actor': self.actor, 'seq': self.seq, 'startOp': self.start_op,
            'time': 0, 'message': '', 'deps': list(heads), 'ops': ops})
        self.start_op += n_ops
        return buf


def build_run(path, n_docs=5, rounds=6, checkpoint_at=2, seed=0,
              exact_device=False, mirror=False, free_doc=None,
              compact_every=None):
    """Run the scripted workload into a fresh durability dir. Returns
    (pre_crash_saves {doc_id: save bytes}, freed doc ids).
    `compact_every=k` forces an INCREMENTAL per-doc compaction every k
    rounds (a chain of segments over the base snapshot) — the recovery
    under test must stitch per-doc generations back together."""
    mgr = DurableFleet(path, exact_device=exact_device)
    handles = mgr.init_docs(n_docs)
    scripts = [_DocScript(i) for i in range(n_docs)]
    rng = random.Random(seed)
    freed = []
    for r in range(rounds):
        per_doc = []
        for d in range(n_docs):
            if handles[d].get('frozen') or (r > 0 and rng.random() < 0.15):
                per_doc.append([])
                continue
            per_doc.append([scripts[d].make(
                fleet_backend.get_heads(handles[d]), rng)])
        out = mgr.apply_changes(handles, per_doc, mirror=mirror)
        handles, _patches, errors = out
        assert not any(errors), f'clean workload rejected: {errors}'
        if r == checkpoint_at:
            mgr.checkpoint()
        if free_doc is not None and r == rounds - 2 and \
                not handles[free_doc].get('frozen'):
            fleet_backend.free_docs([handles[free_doc]])
            freed.append(free_doc)
        if compact_every and r != checkpoint_at and \
                (r + 1) % compact_every == 0:
            mgr.maybe_compact(force=True)
    saves = {d: bytes(fleet_backend.save(handles[d]))
             for d in range(n_docs) if not handles[d].get('frozen')}
    mgr.close()
    return saves, freed


# ---------------------------------------------------------------------------
# expectation model (independent of the recovery code path)
# ---------------------------------------------------------------------------


def journal_record_spans(path):
    """Per-RECORD layout of the manifest's journal in a CLEAN
    (pre-fault) dir. Returns (jpath, data, spans, frame_bounds): spans
    aligns index-for-index with read_state()['journal_records'] and
    carries each record's payload byte span plus `req_end` — the offset
    that must be fully on disk for the record to survive a truncation
    (frame end for per-record frames; the record's own payload end for
    columnar batch frames, whose tables and per-record CRCs precede the
    payloads). frame_bounds lists outer frame (start, end) pairs."""
    st = D.read_state(path)
    jpath = os.path.join(path, st['manifest']['journal'])
    data = open(jpath, 'rb').read()
    spans = []
    frame_bounds = []
    off = int(st['manifest'].get('journal_offset') or 0)
    while off < len(data):
        kind, doc_id, payload, end, status = D._frame_at(data, off)
        assert status == 'ok', f'clean journal has a bad frame: {status}'
        if kind == D.KIND_BATCH:
            dids, _rcrcs, starts, ends, expected_end = D._batch_spans(
                data, off, doc_id, len(data))
            for i in range(doc_id):
                spans.append({'kind': D.KIND_CHANGE, 'did': int(dids[i]),
                              'pay': (int(starts[i]), int(ends[i])),
                              'req_end': int(ends[i]), 'batch': True})
            frame_bounds.append((off, expected_end))
            off = expected_end
        else:
            spans.append({'kind': kind, 'did': doc_id,
                          'pay': (end - 4 - len(payload), end - 4),
                          'req_end': end, 'batch': False})
            frame_bounds.append((off, end))
            off = end
    return jpath, data, spans, frame_bounds


def expected_saves(path, surviving_filter, quarantine_snapshot_doc=None):
    """Per-doc save() bytes a correct recovery must produce, computed by
    replaying the surviving record set through a fresh clean fleet.
    `surviving_filter(i, frame)` says whether the i-th journal frame
    survives the fault; `quarantine_snapshot_doc` marks one snapshot doc
    whose baseline was rotted away (it restarts empty)."""
    st = D.read_state(path)
    baseline = dict(st['docs'])
    queued = {d: list(v) for d, v in st['queued'].items()}
    if quarantine_snapshot_doc is not None:
        baseline.pop(quarantine_snapshot_doc, None)
        queued.pop(quarantine_snapshot_doc, None)
    per = {d: [] for d in baseline}
    exists = set(baseline)
    broken = set()
    freed_in_journal = set()
    for i, (kind, did, payload) in enumerate(st['journal_records']):
        if not surviving_filter(i, (kind, did, payload)):
            # the victim loses this record AND every later one of its
            # own (recovery either skips them by policy or the causal
            # gate holds them back — same save() either way)
            if did is not None:
                broken.add(did)
            continue
        if kind == D.KIND_INIT:
            exists.add(did)
            per.setdefault(did, [])
        elif kind == D.KIND_CHANGE:
            if did in broken:
                continue
            exists.add(did)
            per.setdefault(did, []).append(bytes(payload))
        elif kind == D.KIND_FREE:
            exists.discard(did)
            per.pop(did, None)
            broken.discard(did)
            freed_in_journal.add(did)
    if quarantine_snapshot_doc is not None and \
            quarantine_snapshot_doc not in freed_in_journal:
        # its journal suffix cannot apply without the baseline — the doc
        # restarts empty (unless a surviving FREE record deleted it)
        exists.add(quarantine_snapshot_doc)
        per[quarantine_snapshot_doc] = []
    fleet = DocFleet(doc_capacity=8, key_capacity=64)
    handles = {}
    ids = sorted(exists)
    for did in ids:
        if baseline.get(did):
            handles[did] = fleet_backend.load(bytes(baseline[did]), fleet)
        else:
            handles[did] = fleet_backend.init(fleet)
    work_ids = [d for d in ids if queued.get(d) or per.get(d)]
    if work_ids:
        out, _p, errs = fleet_backend.apply_changes_docs(
            [handles[d] for d in work_ids],
            [list(queued.get(d, [])) + list(per.get(d, []))
             for d in work_ids],
            mirror=False, on_error='quarantine')
        assert not any(errs), f'expectation replay rejected: {errs}'
        for did, handle in zip(work_ids, out):
            handles[did] = handle
    return {did: bytes(fleet_backend.save(handles[did])) for did in ids}


# ---------------------------------------------------------------------------
# fault injection + verification
# ---------------------------------------------------------------------------


def _recover_and_compare(case, faulted_dir, expect, mode, failures,
                         expect_torn=False, expect_rot=False,
                         expect_damage=False, expect_quarantined=(),
                         allow_differ=()):
    h0 = D.durability_stats()
    try:
        mgr, handles, report = DurableFleet.recover(
            faulted_dir, **{'exact_device': MODES[mode]['exact_device'],
                            'mirror': MODES[mode]['mirror']})
    except AutomergeError as exc:
        failures.append(f'{case}: typed recovery failure (should have '
                        f'contained): {type(exc).__name__}: {exc}')
        return None
    except Exception as exc:        # noqa: BLE001 - the harness net
        failures.append(f'{case}: UNTYPED escape: '
                        f'{type(exc).__name__}: {exc}')
        return None
    try:
        got = {did: bytes(fleet_backend.save(h))
               for did, h in handles.items()}
        if sorted(got) != sorted(expect):
            failures.append(f'{case}: doc set {sorted(got)} != expected '
                            f'{sorted(expect)} (report {report})')
            return report
        for did in sorted(expect):
            if did in allow_differ:
                # the fault took this doc's newest persisted copy; it
                # recovers to an OLDER generation (segment-chain rot) —
                # equality is asserted for everyone else
                continue
            if got[did] != expect[did]:
                failures.append(
                    f'{case}: doc {did} save bytes diverge from the '
                    f'checkpoint+suffix expectation (report {report})')
        h1 = D.durability_stats()
        if expect_torn and h1['journal_truncations'] <= \
                h0['journal_truncations']:
            failures.append(f'{case}: torn tail not counted')
        if expect_rot and h1['rotted_records'] <= h0['rotted_records']:
            failures.append(f'{case}: rotted record not counted')
        if expect_damage and not (report.rotted_records or
                                  report.torn_tail_bytes):
            failures.append(f'{case}: damage not reported at all')
        for did in expect_quarantined:
            if did not in report.quarantined:
                failures.append(f'{case}: doc {did} expected in '
                                f'quarantine, report {report}')
        if len(report.quarantined) > 1:
            failures.append(f'{case}: blast radius {len(report.quarantined)}'
                            f' docs > 1 (report {report})')
        return report
    finally:
        mgr.close()


def run_crashtest(n_seeds=None, n_points=None, modes=None, verbose=False):
    """Returns {'cases', 'failures': [...]}; empty failures = green."""
    n_seeds = n_seeds if n_seeds is not None else \
        int(os.environ.get('CRASH_SEEDS', '2'))
    n_points = n_points if n_points is not None else \
        int(os.environ.get('CRASH_POINTS', '4'))
    modes = modes or list(os.environ.get('CRASH_MODES',
                                         'lww,lww-mirror,exact').split(','))
    failures = []
    cases = 0
    root = tempfile.mkdtemp(prefix='crashtest-')
    try:
        for mode in modes:
            cfg = MODES[mode]
            for seed in range(n_seeds):
                base = os.path.join(root, f'{mode}-{seed}')
                # 12 docs/round crosses the columnar-batch threshold
                # (_BATCH_MIN); skip-rounds drop below it, so both frame
                # formats land in one journal
                build_run(base, n_docs=12, seed=seed,
                          free_doc=4 if seed % 2 else None,
                          exact_device=cfg['exact_device'],
                          mirror=cfg['mirror'])
                jpath, jdata, spans, frame_bounds = \
                    journal_record_spans(base)
                jname = os.path.basename(jpath)
                rng = random.Random(1000 + seed)

                def faulted(tag, mutate):
                    """Copy the dir, apply `mutate(journal bytes) ->
                    bytes` to the journal, return the copy's path."""
                    dst = os.path.join(root, f'{mode}-{seed}-{tag}')
                    if os.path.exists(dst):
                        shutil.rmtree(dst)
                    shutil.copytree(base, dst)
                    with open(os.path.join(dst, jname), 'wb') as f:
                        f.write(mutate(jdata))
                    return dst

                # ---- kill at random offset (journal truncation)
                offsets = [rng.randrange(len(jdata) + 1)
                           for _ in range(n_points)]
                # always include the torn-final-frame case explicitly
                if frame_bounds:
                    s, e = frame_bounds[-1]
                    offsets.append(rng.randrange(s + 1, e))
                for j, cut in enumerate(offsets):
                    cases += 1
                    tag = f'kill@{cut}'
                    dst = faulted(f'kill{j}', lambda d, c=cut: d[:c])
                    expect = expected_saves(
                        base, lambda i, fr, c=cut: spans[i]['req_end'] <= c)
                    torn = any(s < cut < e for s, e in frame_bounds)
                    _recover_and_compare(f'{mode}/{seed}/{tag}', dst,
                                         expect, mode, failures,
                                         expect_torn=torn)

                # ---- bit rot inside CHANGE record payloads (journal)
                change_recs = [(i, sp) for i, sp in enumerate(spans)
                               if sp['kind'] == D.KIND_CHANGE]
                for j in range(min(n_points, len(change_recs))):
                    cases += 1
                    ri, sp = change_recs[rng.randrange(len(change_recs))]
                    bit_at = rng.randrange(sp['pay'][0], sp['pay'][1])
                    bit = 1 << rng.randrange(8)

                    def rot(data, at=bit_at, b=bit):
                        out = bytearray(data)
                        out[at] ^= b
                        return bytes(out)

                    dst = faulted(f'rot{j}', rot)
                    expect = expected_saves(
                        base, lambda i, fr, ri=ri: i != ri)
                    # payload flips in batch frames are ALWAYS attributed
                    # through the table crcs; in a per-record frame that
                    # is also the journal's final frame, a flip may read
                    # as a torn tail instead — either way damage must be
                    # reported
                    is_last_plain = not sp['batch'] and \
                        ri == len(spans) - 1
                    _recover_and_compare(
                        f'{mode}/{seed}/rot@{bit_at}', dst, expect, mode,
                        failures, expect_rot=not is_last_plain,
                        expect_damage=is_last_plain)

                # ---- bit rot inside a snapshot DOC frame
                st = D.read_state(base)
                snap_name = st['manifest'].get('snapshot')
                if snap_name and st['docs']:
                    cases += 1
                    sdata = open(os.path.join(base, snap_name), 'rb').read()
                    # find a DOC frame to hit (skip magic prefix)
                    off = len(D.SNAP_MAGIC)
                    doc_frames = []
                    while off < len(sdata):
                        kind, did, _p, end, status = D._frame_at(sdata, off)
                        assert status == 'ok'
                        if kind == D.KIND_DOC:
                            doc_frames.append((off, end, did))
                        off = end
                    s, e, victim = doc_frames[
                        rng.randrange(len(doc_frames))]
                    # flip inside the payload region so the damage is
                    # attributable (structural magic/END rot is covered
                    # by the generation-fallback tests)
                    at = rng.randrange(s + 15, e - 4)
                    rotted = bytearray(sdata)
                    rotted[at] ^= 1 << rng.randrange(8)
                    dst = os.path.join(root, f'{mode}-{seed}-snaprot')
                    if os.path.exists(dst):
                        shutil.rmtree(dst)
                    shutil.copytree(base, dst)
                    with open(os.path.join(dst, snap_name), 'wb') as f:
                        f.write(bytes(rotted))
                    expect = expected_saves(
                        base, lambda i, fr: True,
                        quarantine_snapshot_doc=victim)
                    _recover_and_compare(
                        f'{mode}/{seed}/snaprot@{at}', dst, expect, mode,
                        failures, expect_quarantined=(victim,))

                # ---- checkpoint-protocol crash points
                for point in ('snapshot-temp-written', 'snapshot-renamed',
                              'journal-rotated', 'manifest-flipped'):
                    cases += 1
                    dst = os.path.join(root, f'{mode}-{seed}-ckpt-{point}')
                    if os.path.exists(dst):
                        shutil.rmtree(dst)
                    pre, _freed = build_run(
                        dst, seed=seed, exact_device=cfg['exact_device'],
                        mirror=cfg['mirror'], checkpoint_at=rng.randrange(
                            1, 5))
                    mgr2, rec, _rep = DurableFleet.recover(
                        dst, exact_device=cfg['exact_device'],
                        mirror=cfg['mirror'])
                    mgr2.__class__ = _CrashingFleet
                    mgr2.crash_at = point
                    try:
                        mgr2.checkpoint()
                        failures.append(f'{mode}/{seed}/ckpt-{point}: '
                                        f'fault hook never fired')
                    except _SimulatedCrash:
                        pass
                    # abandon mgr2 (simulated death) and recover the dir:
                    # every step must preserve the full pre-crash state
                    expect = {did: bytes(fleet_backend.save(h))
                              for did, h in rec.items()}
                    _recover_and_compare(f'{mode}/{seed}/ckpt-{point}',
                                         dst, expect, mode, failures)

                # ---- incremental per-doc compaction (segment chain):
                # recovery stitches per-doc generations — base snapshot,
                # K segments (incl. a freed doc's tombstone), live
                # journal — back to byte-identical state, and survives
                # journal truncation + compaction-protocol crashes
                seg_base = os.path.join(root, f'{mode}-{seed}-seg')
                pre, _freed = build_run(
                    seg_base, n_docs=12, rounds=8, seed=seed,
                    free_doc=3 if seed % 2 else None,
                    exact_device=cfg['exact_device'], mirror=cfg['mirror'],
                    compact_every=2)
                st_seg = D.read_state(seg_base)
                assert len(st_seg['manifest'].get('chain') or []) > 1, \
                    'segment workload produced no chain'
                cases += 1
                _recover_and_compare(
                    f'{mode}/{seed}/segments-clean', seg_base,
                    expected_saves(seg_base, lambda i, fr: True), mode,
                    failures)
                # truncation of the LIVE journal over a chain
                jpath2, jdata2, spans2, fb2 = journal_record_spans(seg_base)
                if len(jdata2):
                    cases += 1
                    cut = rng.randrange(len(jdata2) + 1)
                    dst = os.path.join(root, f'{mode}-{seed}-seg-kill')
                    if os.path.exists(dst):
                        shutil.rmtree(dst)
                    shutil.copytree(seg_base, dst)
                    with open(os.path.join(dst,
                                           os.path.basename(jpath2)),
                              'wb') as f:
                        f.write(jdata2[:cut])
                    expect = expected_saves(
                        seg_base,
                        lambda i, fr, c=cut: spans2[i]['req_end'] <= c)
                    torn = any(s < cut < e for s, e in fb2)
                    _recover_and_compare(f'{mode}/{seed}/seg-kill@{cut}',
                                         dst, expect, mode, failures,
                                         expect_torn=torn)
                # rot inside the NEWEST segment's DOC frame: the victim
                # falls back to an older generation (stitched), everyone
                # else stays byte-identical, damage reports typed
                chain = st_seg['manifest']['chain']
                sdata = open(os.path.join(seg_base, chain[-1]),
                             'rb').read()
                off = len(D.SNAP_MAGIC)
                doc_frames = []
                while off < len(sdata):
                    kind, did, _p, end, status = D._frame_at(sdata, off)
                    assert status == 'ok'
                    if kind == D.KIND_DOC:
                        doc_frames.append((off, end, did))
                    off = end
                if doc_frames:
                    cases += 1
                    s, e, victim = doc_frames[
                        rng.randrange(len(doc_frames))]
                    at = rng.randrange(s + 15, e - 4)
                    rotted = bytearray(sdata)
                    rotted[at] ^= 1 << rng.randrange(8)
                    dst = os.path.join(root, f'{mode}-{seed}-seg-rot')
                    if os.path.exists(dst):
                        shutil.rmtree(dst)
                    shutil.copytree(seg_base, dst)
                    with open(os.path.join(dst, chain[-1]), 'wb') as f:
                        f.write(bytes(rotted))
                    expect = expected_saves(seg_base, lambda i, fr: True)
                    _recover_and_compare(
                        f'{mode}/{seed}/seg-rot@{at}', dst, expect, mode,
                        failures, expect_quarantined=(victim,),
                        allow_differ=(victim,))
                # compaction-protocol crash points (same _fault hooks as
                # the full checkpoint)
                for point in ('snapshot-temp-written', 'snapshot-renamed',
                              'journal-rotated', 'manifest-flipped'):
                    cases += 1
                    dst = os.path.join(root,
                                       f'{mode}-{seed}-seg-{point}')
                    if os.path.exists(dst):
                        shutil.rmtree(dst)
                    build_run(dst, n_docs=8, rounds=6, seed=seed,
                              exact_device=cfg['exact_device'],
                              mirror=cfg['mirror'], compact_every=3)
                    mgr2, rec, _rep = DurableFleet.recover(
                        dst, exact_device=cfg['exact_device'],
                        mirror=cfg['mirror'])
                    expect = {did: bytes(fleet_backend.save(h))
                              for did, h in rec.items()}
                    # dirty one doc so compact() has churn to persist
                    did0 = sorted(rec)[0]
                    sc = _DocScript(99)
                    sc.actor = f'{seed:02x}ee' * 8
                    buf = sc.make(
                        fleet_backend.get_heads(rec[did0]), rng)
                    out_h, _p, errs = mgr2.apply_changes(
                        [rec[did0]], [[buf]])
                    assert not any(errs)
                    expect[did0] = bytes(fleet_backend.save(out_h[0]))
                    mgr2.__class__ = _CrashingFleet
                    mgr2.crash_at = point
                    try:
                        mgr2.compact()
                        failures.append(f'{mode}/{seed}/seg-{point}: '
                                        f'fault hook never fired')
                    except _SimulatedCrash:
                        pass
                    _recover_and_compare(f'{mode}/{seed}/seg-{point}',
                                         dst, expect, mode, failures)

                if verbose:
                    print(f'# crashtest {mode} seed {seed}: '
                          f'{cases} cases so far, '
                          f'{len(failures)} failures', file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {'cases': cases, 'failures': failures}


def main():
    start = time.perf_counter()
    stats = run_crashtest(
        n_seeds=int(os.environ.get('CRASH_SEEDS', '3')),
        n_points=int(os.environ.get('CRASH_POINTS', '6')),
        verbose=True)
    took = time.perf_counter() - start
    print(f"crashtest: {stats['cases']} cases, "
          f"{len(stats['failures'])} failures ({took:.1f}s)")
    for row in stats['failures'][:40]:
        print('  ', row)
    return 1 if stats['failures'] else 0


if __name__ == '__main__':
    sys.exit(main())
