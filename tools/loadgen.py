#!/usr/bin/env python
"""Zipf-tenant open-loop load generator + chaos client for the service.

The standing scenario testbed for ``automerge_tpu.service`` (ISSUE-7):
an OPEN-LOOP arrival process (arrivals do not wait for completions — the
honest overload model; a closed loop self-throttles and hides collapse)
over a Zipf-skewed tenant population (tenant 1 is the whale, the tail is
long — the distribution under which per-tenant fairness actually earns
its keep), with an optional CHAOS CLIENT that does everything a hostile
or broken real client does:

- corrupts sync/apply payloads in flight (seeded bit flips/truncation on
  a per-attempt transport draw, so service-side retries genuinely
  re-draw — some attempts arrive clean);
- violates deadlines (submits work with deadlines it cannot meet);
- replays already-delivered changes (idempotency probe);
- floods (bursts far past its token bucket, eating typed throttles);
- disconnects sessions mid-flight and abandons their queued work.

Three standard legs — ``clean``, ``chaos``, ``overload`` (2x arrival
rate into reduced admission capacity) — each reporting p50/p95/p99
request latency, sustained rounds/s and requests/s, every rejection
bucketed BY TYPE (an untyped escape anywhere fails the run), brownout
ladder transitions, and a convergence audit: every edit session's doc
must be byte-identical to an unloaded control fleet fed exactly the
committed requests, and every sync session's client replica must reach
head-equality with its service doc after a drain. Every leg also runs
the SLO AUDIT (ISSUE-10): the service SloRegistry's per-tenant outcome
tallies must match the client-observed typed outcomes EXACTLY, so a
double-count or missed-reject in the accounting plane fails the leg —
and ``latency_step=(tick, extra_s)`` injects a synthetic mid-leg
latency regression for timing the burn-rate alert's detection. Used by
tests/test_service_chaos.py (small doses) and bench.py's ``service``
and ``slo`` sections (10k sessions).

Standalone:  python tools/loadgen.py            # default three legs
             LOADGEN_SESSIONS=10000 LOADGEN_REQUESTS=40000 \
             python tools/loadgen.py
"""

import bisect
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import automerge_tpu as A                                     # noqa: E402
from automerge_tpu import backend as host_backend             # noqa: E402
from automerge_tpu.backend import get_change_by_hash          # noqa: E402
from automerge_tpu.columnar import (encode_change,            # noqa: E402
                                    decode_change_meta)
from automerge_tpu.errors import AutomergeError                # noqa: E402
from automerge_tpu.fleet import backend as fleet_backend      # noqa: E402
from automerge_tpu.fleet.backend import DocFleet              # noqa: E402
from automerge_tpu.fleet.faults import LossyLink              # noqa: E402
from automerge_tpu.control import Controller                  # noqa: E402
from automerge_tpu.observability.slo import outcome_class     # noqa: E402
from automerge_tpu.service import Backoff, DocService         # noqa: E402
from automerge_tpu.shard import ShardRouter, shard_stats      # noqa: E402

__all__ = ['ZipfSampler', 'ChaosClient', 'run_leg', 'run_standard_legs',
           'run_shard_leg']


class ZipfSampler:
    """Zipf(s) over n tenants: weight(k) ~ 1/k^s, sampled via one
    bisect on the cumulative table."""

    def __init__(self, n, s=1.2):
        weights = [1.0 / (k + 1) ** s for k in range(n)]
        total = sum(weights)
        self.cum = []
        acc = 0.0
        for w in weights:
            acc += w / total
            self.cum.append(acc)

    def draw(self, rng):
        return bisect.bisect_left(self.cum, rng.random())


class ChaosClient:
    """Per-attempt transport mischief, seeded. ``wrap(payload)`` returns
    a payload_fn whose every call is one transport draw: usually the
    clean bytes, sometimes flipped/truncated/None. The service's retry
    path re-draws through it, so corruption is genuinely transient."""

    def __init__(self, seed, p_corrupt=0.3, p_truncate=0.1, p_drop=0.05):
        self.rng = random.Random(seed)
        self.p_corrupt = p_corrupt
        self.p_truncate = p_truncate
        self.p_drop = p_drop
        self.draws = 0
        self.corrupted = 0

    def _mangle_one(self, buf):
        roll = self.rng.random()
        if roll < self.p_drop:
            self.corrupted += 1
            return None
        if roll < self.p_drop + self.p_truncate and len(buf) > 1:
            self.corrupted += 1
            return buf[:self.rng.randrange(1, len(buf))]
        if roll < self.p_drop + self.p_truncate + self.p_corrupt and buf:
            self.corrupted += 1
            out = bytearray(buf)
            pos = self.rng.randrange(len(out))
            out[pos] ^= 1 << self.rng.randrange(8)
            return bytes(out)
        return buf

    def wrap_changes(self, buffers):
        """payload_fn for an 'apply' request (list of change bytes)."""
        clean = [bytes(b) for b in buffers]

        def draw():
            self.draws += 1
            out = []
            for buf in clean:
                got = self._mangle_one(buf)
                if got is None:
                    return None           # transport delivered nothing
                out.append(got)
            return out
        return draw

    def wrap_message(self, message):
        """payload_fn for a 'sync' request (one message or None)."""
        clean = None if message is None else bytes(message)

        def draw():
            self.draws += 1
            if clean is None:
                return None
            return self._mangle_one(clean)
        return draw


class _EditSession:
    """An apply-only client: a stream of seq-consecutive changes from
    one actor. Tracks what COMMITTED for the control-fleet audit."""

    __slots__ = ('session', 'actor', 'seq', 'committed', 'inflight')

    def __init__(self, session, actor):
        self.session = session
        self.actor = actor
        self.seq = 0
        self.committed = []        # payloads whose tickets resolved ok
        self.inflight = []         # (ticket, payload)

    def next_payload(self, rng):
        self.seq += 1
        return [encode_change({
            'actor': self.actor, 'seq': self.seq, 'startOp': self.seq,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{rng.randrange(8)}',
                     'value': rng.randrange(10_000), 'datatype': 'int',
                     'pred': []}]})]

    def harvest(self):
        still = []
        for ticket, payload in self.inflight:
            if not ticket.done:
                still.append((ticket, payload))
            elif ticket.status == 'ok':
                self.committed.append(payload)
        self.inflight = still


class _SyncSession:
    """A sync client: a host-backend replica editing locally and
    reconciling with its service doc through the sync protocol."""

    __slots__ = ('session', 'actor', 'doc', 'state', 'seq', '_prev_state')

    def __init__(self, session, actor):
        self.session = session
        self.actor = actor
        doc = A.init(actor)
        self.doc = A.frontend.get_backend_state(doc, f'loadgen-{actor}')
        self.state = host_backend.init_sync_state()
        self.seq = 0
        self._prev_state = None

    def edit(self, rng):
        """One local change on the client replica (seq-consecutive,
        one op per change, deps = current replica heads)."""
        self.seq += 1
        change = encode_change({
            'actor': self.actor, 'seq': self.seq, 'startOp': self.seq,
            'time': 0, 'message': '',
            'deps': host_backend.get_heads(self.doc),
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f's{rng.randrange(4)}',
                     'value': rng.randrange(10_000), 'datatype': 'int',
                     'pred': []}]})
        self.doc, _ = host_backend.apply_changes(self.doc, [change])

    def generate(self):
        self._prev_state = self.state
        self.state, message = host_backend.generate_sync_message(
            self.doc, self.state)
        return message

    def rollback(self):
        """The generated message never left the client (admission
        refused the submit): restore the pre-generate sync state, or the
        optimistic sentHashes would poison the handshake exactly like a
        dropped wire message."""
        if self._prev_state is not None:
            self.state = self._prev_state

    def reconnect(self):
        """Client-side reconnect: fresh sync state (idempotent delivery
        makes this always safe; it costs re-advertisement only)."""
        self.state = host_backend.init_sync_state()

    def receive(self, reply):
        if reply is None:
            return
        try:
            self.doc, self.state, _ = host_backend.receive_sync_message(
                self.doc, self.state, bytes(reply))
        except AutomergeError:
            pass                   # corrupt reply == drop (containment)


def run_leg(name, *, sessions=1000, tenants=64, zipf_s=1.2,
            requests=10_000, arrivals_per_tick=64, sync_fraction=0.25,
            chaos=False, overload=False, seed=0, exact_device=False,
            durable_dir=None, fleet=None, deadline_s=None,
            service_kwargs=None, max_ticks=200_000, convergence=True,
            tick_dt=None, collect_saves=False, latency_step=None):
    """One leg. Returns the report dict (see module docstring).

    `tick_dt` switches the service onto a FAKE clock advanced by that
    many seconds per pump — the whole leg becomes a deterministic
    function of its seed (the cross-device-mode byte-identity tests run
    the same script twice and diff the saves). `collect_saves` adds
    `session_saves` ({session_id: (actor, save_hex)}) to the report.

    `latency_step=(tick, extra_s)` injects a SYNTHETIC latency
    regression mid-leg (requires `tick_dt`): from that tick until the
    leg's arrivals end, every pump advances the fake clock by an extra
    `extra_s`, so every in-flight request's measured latency jumps by
    it — the controlled fault the SLO fast-window burn alert must catch
    (the bench `slo` section and the acceptance test time its
    detection). The report then carries `slo_step_tick` and
    `slo_alerts`.

    Every leg whose service keeps the default SLO accounting ends with
    the SLO AUDIT: the registry's per-tenant outcome tallies must match
    the client-side typed-outcome counts EXACTLY (`slo_audit` in the
    report; tools and main() fail on any mismatch) — the double-count /
    missed-reject detector for the accounting plane under quarantine
    storms."""
    rng = random.Random(seed)
    zipf = ZipfSampler(tenants, zipf_s)
    chaos_client = ChaosClient(seed + 1) if chaos else None

    durable = None
    if durable_dir is not None:
        from automerge_tpu.fleet.durability import DurableFleet
        durable = DurableFleet(durable_dir, exact_device=exact_device,
                               fsync_bytes=1 << 16)
    elif fleet is None:
        fleet = DocFleet(exact_device=exact_device)
    kwargs = dict(tenant_rate=500.0, tenant_burst=200.0, tenant_queue=256,
                  max_queued=max(64, sessions * 2), batch_limit=4096)
    if overload:
        # 2x overload: offered load is twice what the service serves per
        # tick (batch_limit pins per-tick capacity at the base arrival
        # rate), into halved admission headroom — backlog builds, the
        # pressure signal sustains, and the brownout ladder must engage
        # while every rejection stays typed and fair
        # max_queued bounds absolute BACKLOG (latency), not sessions: at
        # 2x offered load the queue pins against it and the sustained
        # queue-pressure signal is what walks the brownout ladder
        kwargs.update(tenant_rate=125.0, tenant_burst=50.0,
                      tenant_queue=64,
                      max_queued=max(32, min(512, sessions)),
                      batch_limit=max(32, arrivals_per_tick))
        arrivals_per_tick *= 2
    if service_kwargs:
        kwargs.update(service_kwargs)
    if latency_step is not None and tick_dt is None:
        raise ValueError('latency_step needs the tick_dt fake clock')
    _clk = [0.0]
    if tick_dt is not None:
        kwargs.setdefault('clock', lambda: _clk[0])
    service = DocService(fleet=fleet, durable=durable, **kwargs)
    _inject = [False]              # latency_step currently applying

    def pump():
        if _inject[0]:
            # the injected regression: age every in-flight request by
            # extra_s before the tick serves it
            _clk[0] += latency_step[1]
        service.pump()
        if tick_dt is not None:
            _clk[0] += tick_dt

    tenant_names = [f'tenant{t}' for t in range(tenants)]
    tenant_of_session = [zipf.draw(rng) for _ in range(sessions)]
    raw = service.open_sessions(
        [tenant_names[t] for t in tenant_of_session])
    by_tenant = {}
    clients = []
    for i, session in enumerate(raw):
        # sessions draw from a bounded actor pool: the fleet interns
        # actor strings fleet-wide with a 256-actor ceiling, and actor
        # seq numbering is PER DOCUMENT, so distinct sessions (distinct
        # docs) sharing an actor string are fully independent
        actor = f'{i % 192:08x}' + 'ab' * 12
        if rng.random() < sync_fraction:
            client = _SyncSession(session, actor)
        else:
            client = _EditSession(session, actor)
        clients.append(client)
        by_tenant.setdefault(tenant_of_session[i], []).append(client)

    counts = {'ok': 0}
    # the client-side half of the SLO audit: every typed outcome this
    # client observes, tallied (tenant, budget class) — the registry's
    # server-side tallies must match these EXACTLY
    client_tally = {}
    latencies = []
    untyped = 0
    submitted = 0
    ticks = 0
    disconnected = 0
    replayed = 0

    def tally(tenant, error):
        key = (tenant, outcome_class(error))
        client_tally[key] = client_tally.get(key, 0) + 1

    def note(ticket):
        nonlocal untyped
        tally(ticket.tenant, ticket.error)
        if ticket.status == 'ok':
            counts['ok'] += 1
            if ticket.latency is not None:
                latencies.append(ticket.latency)
        else:
            err = ticket.error
            key = type(err).__name__
            counts[key] = counts.get(key, 0) + 1
            if not isinstance(err, AutomergeError):
                untyped += 1

    tickets = []

    def submit(client, kind, payload=None, payload_fn=None, timeout=None,
               priority=1):
        nonlocal untyped, submitted
        try:
            ticket = service.submit(client.session, kind, payload,
                                    payload_fn=payload_fn,
                                    timeout=timeout, priority=priority)
        except AutomergeError as exc:
            key = type(exc).__name__
            counts[key] = counts.get(key, 0) + 1
            tally(client.session.tenant, exc)
            return None
        except Exception as exc:       # would be an untyped escape
            counts[f'UNTYPED:{type(exc).__name__}'] = \
                counts.get(f'UNTYPED:{type(exc).__name__}', 0) + 1
            untyped += 1
            return None
        submitted += 1
        tickets.append((ticket, client))
        return ticket

    start = time.perf_counter()
    while (submitted < requests or not service.idle()) and \
            ticks < max_ticks:
        ticks += 1
        if latency_step is not None:
            # the regression applies only while arrivals keep coming
            # (mid-leg): the drain after the loop must converge clean
            _inject[0] = ticks >= latency_step[0] and submitted < requests
        # -- arrivals (open loop: these do not wait for completions)
        n_arrive = min(arrivals_per_tick, requests - submitted)
        for _ in range(max(0, n_arrive)):
            tenant = zipf.draw(rng)
            pool = by_tenant.get(tenant)
            if not pool:
                continue
            client = pool[rng.randrange(len(pool))]
            if client.session.closed:
                continue
            timeout = deadline_s
            priority = 1 if rng.random() < 0.7 else 0
            if chaos and rng.random() < 0.05:
                timeout = 0.0          # deadline the service cannot meet
            if isinstance(client, _EditSession):
                payload = client.next_payload(rng)
                if chaos and rng.random() < 0.3:
                    ticket = submit(client, 'apply',
                                    payload_fn=chaos_client.wrap_changes(
                                        payload),
                                    timeout=timeout, priority=priority)
                else:
                    ticket = submit(client, 'apply', payload,
                                    timeout=timeout, priority=priority)
                if ticket is not None:
                    client.inflight.append((ticket, payload))
                else:
                    # admission refused it: the client keeps the seq and
                    # re-mints it later (a seq gap would poison the
                    # actor's whole suffix)
                    client.seq -= 1
                if chaos and rng.random() < 0.05 and client.committed:
                    # replay an already-committed change (idempotency)
                    replayed += 1
                    submit(client, 'apply',
                           client.committed[rng.randrange(
                               len(client.committed))],
                           timeout=timeout, priority=priority)
            else:
                client.edit(rng)
                message = client.generate()
                if chaos and rng.random() < 0.3:
                    ticket = submit(client, 'sync',
                                    payload_fn=chaos_client.wrap_message(
                                        message),
                                    timeout=timeout, priority=priority)
                else:
                    ticket = submit(client, 'sync', message,
                                    timeout=timeout, priority=priority)
                if ticket is None:
                    # admission refused: the message never left the
                    # client — un-poison sentHashes
                    client.rollback()
            if chaos and rng.random() < 0.002 and \
                    len(service.sessions) > sessions // 2:
                # hard disconnect: abandon the session and its queue
                service.close_session(client.session)
                disconnected += 1
        # -- one service tick
        pump()
        # -- completions: sync clients consume replies, edit clients
        #    book their committed payloads
        still = []
        for ticket, client in tickets:
            if not ticket.done:
                still.append((ticket, client))
                continue
            note(ticket)
            if isinstance(client, _SyncSession) and ticket.status == 'ok':
                client.receive(ticket.result)
        tickets = still
        for client in clients:
            if isinstance(client, _EditSession):
                client.harvest()
    elapsed = time.perf_counter() - start
    _inject[0] = False

    # -- SLO audit: the registry's per-tenant outcome tallies vs the
    #    client-observed typed outcomes. Exact equality or the
    #    accounting plane double-counted / missed a reject somewhere in
    #    the retry/quarantine/disconnect machinery.
    slo_audit = None
    if service.slo is not None:
        pending = sum(1 for t, _ in tickets if not t.done)
        if pending:
            slo_audit = {'skipped': f'{pending} tickets still pending '
                                    f'at max_ticks'}
        else:
            server_tally = {}
            for (tenant, _kind), outcomes in service.slo.tallies().items():
                for cls, n in outcomes.items():
                    key = (tenant, cls)
                    server_tally[key] = server_tally.get(key, 0) + n
            mismatches = []
            for key in sorted(set(server_tally) | set(client_tally)):
                want = client_tally.get(key, 0)
                got = server_tally.get(key, 0)
                if want != got:
                    mismatches.append({'tenant': key[0], 'outcome': key[1],
                                       'client': want, 'registry': got})
            slo_audit = {'pairs_checked': len(set(server_tally) |
                                              set(client_tally)),
                         'mismatches': mismatches}

    # -- drain: finish the sync handshakes fault-free so convergence is
    #    assertable (the wire is quiet, the service keeps admitting)
    converged_sync = drained = 0
    if convergence:
        for client in clients:
            if not isinstance(client, _SyncSession) or \
                    client.session.closed:
                continue
            drained += 1
            # both ends may leave the loaded phase with poisoned
            # handshake state (failed/shredded requests are wire drops);
            # a drain is a RECONNECT — fresh client state, and the
            # service side resets through its own stall machinery
            client.reconnect()
            stalled = 0
            fresh = True
            for _ in range(96):
                message = client.generate()
                ticket = None
                for _ in range(1000):   # ride out throttling, typed —
                    try:                # whale tenants refill at rate
                        ticket = service.submit(client.session, 'sync',
                                                message, priority=5,
                                                reset=fresh)
                        break
                    except AutomergeError:
                        pump()
                fresh = False
                if ticket is None:
                    client.rollback()
                    break
                while not ticket.done:
                    pump()
                if ticket.status != 'ok':
                    client.rollback()   # never processed: un-poison
                    continue
                client.receive(ticket.result)
                service_heads = host_backend.get_heads(
                    client.session.handle)
                client_heads = host_backend.get_heads(client.doc)
                if message is None and ticket.result is None and \
                        service_heads == client_heads:
                    converged_sync += 1
                    break
                stalled += 1
                if stalled % 24 == 23:  # belt-and-braces reconnect
                    client.reconnect()
                    fresh = True

    # -- control audit: an unloaded fleet fed exactly the committed
    #    edits must byte-match the loaded service docs
    mismatches = 0
    audited = 0
    if convergence:
        control_fleet = DocFleet(exact_device=exact_device)
        edit_clients = [c for c in clients
                        if isinstance(c, _EditSession)
                        and not c.session.closed and c.committed]
        if edit_clients:
            control = fleet_backend.init_docs(len(edit_clients),
                                              control_fleet)
            control, _ = fleet_backend.apply_changes_docs(
                control, [[b for payload in c.committed for b in payload]
                          for c in edit_clients], mirror=False)
            for client, ctrl in zip(edit_clients, control):
                audited += 1
                if bytes(host_backend.save(client.session.handle)) != \
                        bytes(host_backend.save(ctrl)):
                    mismatches += 1

    latencies.sort()

    def pct(p):
        if not latencies:
            return None
        return latencies[min(len(latencies) - 1,
                             int(p * len(latencies)))]

    report = {
        'leg': name,
        'sessions': sessions,
        'tenants': tenants,
        'requests_offered': requests,
        'submitted': submitted,
        'completed_ok': counts['ok'],
        'rejections': {k: v for k, v in sorted(counts.items())
                       if k != 'ok'},
        'untyped_escapes': untyped,
        'elapsed_s': round(elapsed, 3),
        'ticks': ticks,
        'rounds_per_s': round(ticks / elapsed, 1) if elapsed else None,
        'requests_per_s': round(counts['ok'] / elapsed, 1)
        if elapsed else None,
        'p50_ms': round(pct(0.50) * 1e3, 3) if latencies else None,
        'p95_ms': round(pct(0.95) * 1e3, 3) if latencies else None,
        'p99_ms': round(pct(0.99) * 1e3, 3) if latencies else None,
        'brownout_stage_final': service.brownout.stage,
        'brownout_transitions': len(service.brownout.transitions),
        'disconnected': disconnected,
        'replayed': replayed,
        'chaos_draws': chaos_client.draws if chaos_client else 0,
        'chaos_corrupted': chaos_client.corrupted if chaos_client else 0,
        'convergence': {
            'edit_docs_audited': audited,
            'edit_mismatches': mismatches,
            'sync_drained': drained,
            'sync_converged': converged_sync,
        } if convergence else None,
        'slo_audit': slo_audit,
    }
    if service.slo is not None:
        report['slo_alerts'] = [
            {'tick': t, 'tenant': tenant, 'kind': kind, 'sli': sli,
             'window': window, 'edge': edge, 'burn': burn}
            for t, tenant, kind, sli, window, edge, burn in
            service.slo.alert_log]
        if latency_step is not None:
            report['slo_step_tick'] = latency_step[0]
    if collect_saves:
        report['session_saves'] = {
            c.session.id: (c.actor,
                           bytes(host_backend.save(c.session.handle)).hex())
            for c in clients if not c.session.closed}
    if durable is not None:
        durable.close()
    return report


class _ShardWriter:
    """One tenant's write stream in shard mode: seq-consecutive changes
    from one actor, at most one apply in flight (seq ordering survives
    router-level retries), failed payloads RETRANSMITTED byte-identical
    (a re-minted seq with fresh content would collide with a copy the
    crash actually preserved — idempotent-by-hash replay is the safe
    retry)."""

    __slots__ = ('name', 'actor', 'seq', 'acked', 'inflight', 'stash')

    def __init__(self, name, actor):
        self.name = name
        self.actor = actor
        self.seq = 0
        self.acked = []          # payloads whose router tickets acked
        self.inflight = None     # (ticket, payload)
        self.stash = None        # failed payload awaiting retransmit

    def next_payload(self, rng):
        if self.stash is not None:
            payload, self.stash = self.stash, None
            return payload
        self.seq += 1
        return [encode_change({
            'actor': self.actor, 'seq': self.seq, 'startOp': self.seq,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{rng.randrange(8)}',
                     'value': rng.randrange(10_000), 'datatype': 'int',
                     'pred': []}]})]


def run_shard_leg(name, *, n_shards=4, tenants=16, requests=800,
                  arrivals_per_tick=8, kills=(), chaos=False, seed=0,
                  lease_ticks=3, tick_dt=0.02, subscribe_fraction=0.2,
                  sync_fraction=0.1, rebalance_after_revive=True,
                  audit_rounds=True, exact_device=False,
                  link_budget=48, max_ticks=60_000, mttr_bound=None,
                  service_kwargs=None, pump_threads=None, repl_every=1,
                  pace=False, control=None, control_window=5,
                  settle_bound=None):
    """The kill-and-recover chaos leg for the shard cluster (ISSUE-11).

    Drives an open-loop workload (applies + subscription pulls + sync
    solicits) through a ``ShardRouter`` while crashing and reviving
    shards on a schedule: ``kills`` is a sequence of
    ``(kill_tick, shard_index, revive_tick)``. With ``chaos=True`` the
    inter-shard replication links are budgeted ``LossyLink``s
    (drop/dup/reorder/truncate/flip), so replication itself rides a
    hostile wire; the budget runs dry before the drain, which is what
    makes the post-quiet audit assertable.

    The two contract audits (run after each revive round when
    ``audit_rounds``, and always at the end):

    - ZERO ACKNOWLEDGED-WRITE LOSS: every change of every acked apply
      is present (by hash) on the tenant's CURRENT home doc — across
      every kill, failover, and rebalance in the schedule.
    - BYTE-IDENTICAL CONVERGENCE: after replication goes quiet, every
      tenant's home doc and replica doc save() to identical bytes.

    Plus the standing properties: zero untyped escapes (every failed
    ticket carries an AutomergeError), and failover MTTR — ticks from
    each kill to the first acked request served by a re-homed tenant's
    replica — reported per kill (``mttr_bound`` asserts a ceiling).

    ``control='active'|'shadow'`` rides a ``control.Controller`` on the
    router's pump (the self-driving leg, ISSUE-20): under ACTIVE
    control the leg's hardcoded ``rebalance_after_revive`` call is
    disabled — post-revive placement healing is exactly the control
    plane's heal lane, and this leg is where it earns that job. The
    leg's ``ok`` then also requires <= 2 direction reversals per policy
    (the anti-oscillation bound), a decision-free CONVERGENCE HOLD (10
    quiet decision windows pumped after the drain — an oscillating
    controller keeps hunting and fails it), and, with ``settle_bound``,
    that the last decision lands within that many ticks of the last
    revive. Both audits (zero acked-write loss, byte-identical
    convergence) run unchanged: a controller that converges by losing
    writes fails the same assert the chaos schedule does."""
    rng = random.Random(seed)
    clk = [0.0]
    link_seed = [seed * 7919 + 13]
    if control is not None and control not in ('active', 'shadow'):
        raise ValueError(f"control must be None, 'active' or 'shadow', "
                         f'got {control!r}')
    ctrl = Controller(mode=control, window=control_window) \
        if control is not None else None
    if control == 'active':
        # the heal lane owns post-revive placement now; the hardcoded
        # rebalance would fight it (and mask it)
        rebalance_after_revive = False

    def link_factory(src, dst):
        if not chaos:
            return None
        link_seed[0] += 1
        return LossyLink(seed=link_seed[0], p_drop=0.05, p_dup=0.02,
                         p_reorder=0.02, p_truncate=0.02, p_flip=0.02,
                         budget=link_budget)

    router = ShardRouter(
        n_shards=n_shards, clock=lambda: clk[0],
        lease_ticks=lease_ticks, link_factory=link_factory,
        exact_device=exact_device, service_kwargs=service_kwargs,
        pump_threads=pump_threads, repl_every=repl_every,
        # paced legs declare the cadence to the router too, so slipped
        # ticks are attributed PER SHARD (Shard.ticks_slipped -> the
        # labeled Prometheus counter), not just counted in this loop
        tick_budget_s=tick_dt if pace else None,
        control=ctrl,
        backoff=Backoff(base=tick_dt, factor=1.5, cap=tick_dt * 16,
                        retries=16, jitter=0.5, seed=seed + 3))
    shard_ids = router.ring.shard_ids()
    tenant_names = [f'tenant{t}' for t in range(tenants)]
    writers = {}
    for i, t in enumerate(tenant_names):
        router.open_tenant(t)
        writers[t] = _ShardWriter(t, f'{i % 192:08x}' + 'cd' * 12)

    counts = {'ok': 0}
    untyped = 0
    submitted = 0
    aux = []                    # subscribe/sync tickets in flight
    audits = []
    mttrs = []                  # one record per kill
    kill_list = sorted(kills)
    revive_pending = []         # (revive_tick, shard_id)
    last_revive_tick = None
    base_health = shard_stats()

    def pump():
        router.pump(now=clk[0])
        clk[0] += tick_dt

    def note_error(err):
        nonlocal untyped
        key = type(err).__name__
        counts[key] = counts.get(key, 0) + 1
        if not isinstance(err, AutomergeError):
            untyped += 1

    def harvest():
        for t, w in writers.items():
            if w.inflight is None:
                continue
            ticket, payload = w.inflight
            if not ticket.done:
                continue
            w.inflight = None
            if ticket.status == 'ok':
                counts['ok'] += 1
                w.acked.append(payload)
                for m in mttrs:
                    if m['mttr_ticks'] is None and t in m['tenants'] and \
                            router.tenant_record(t).home != m['shard']:
                        m['mttr_ticks'] = router.ticks - m['kill_tick']
            else:
                note_error(ticket.error)
                w.stash = payload        # retransmit the SAME bytes
        still = []
        for ticket in aux:
            if not ticket.done:
                still.append(ticket)
                continue
            if ticket.status == 'ok':
                counts['ok'] += 1
            else:
                note_error(ticket.error)
        aux[:] = still

    def writers_idle():
        return all(w.inflight is None for w in writers.values())

    def drain_quiet(budget=1200):
        for _ in range(budget):
            if router.idle() and router.replication_quiet() and \
                    not router.migrating() and writers_idle() and not aux:
                return True
            pump()
            harvest()
        return False

    def audit(tag):
        checked = lost = pairs = mismatched = homeless = 0
        for t, w in writers.items():
            rec = router.tenant_record(t)
            if rec.home is None or rec.session is None:
                homeless += 1
                continue
            for payload in w.acked:
                for b in payload:
                    checked += 1
                    h = decode_change_meta(bytes(b), True)['hash']
                    if get_change_by_hash(rec.session.handle, h) is None:
                        lost += 1
            if rec.replica_handle is not None:
                pairs += 1
                home_bytes = bytes(host_backend.save(rec.session.handle))
                rep_bytes = bytes(host_backend.save(rec.replica_handle))
                if home_bytes != rep_bytes:
                    mismatched += 1
        record = {'tag': tag, 'tick': router.ticks,
                  'acked_changes_checked': checked, 'acked_lost': lost,
                  'replica_pairs': pairs,
                  'replica_mismatches': mismatched,
                  'homeless_tenants': homeless}
        audits.append(record)
        return record

    start = time.perf_counter()
    slipped = 0
    while submitted < requests or not writers_idle() or aux or \
            kill_list or revive_pending:
        if router.ticks >= max_ticks:
            break
        if pace:
            # the serving tick is a CADENCE (tick_dt bounds batching
            # latency): sleep to the tick boundary, and when the tick's
            # work overran it, count the slip — a box whose per-tick
            # work does not fit the cadence shows it here instead of
            # silently reporting free-run throughput
            deadline = start + router.ticks * tick_dt
            wait = deadline - time.perf_counter()
            if wait > 0:
                time.sleep(wait)
            else:
                slipped += 1
        while kill_list and router.ticks >= kill_list[0][0]:
            ktick, sidx, rtick = kill_list.pop(0)
            sid = shard_ids[sidx]
            doomed = set(router.tenants_on(sid))
            router.kill_shard(sid)
            mttrs.append({'shard': sid, 'kill_tick': router.ticks,
                          'tenants': doomed, 'mttr_ticks': None})
            revive_pending.append((rtick, sid))
        for rtick, sid in list(revive_pending):
            if router.ticks >= rtick:
                revive_pending.remove((rtick, sid))
                router.revive_shard(sid)
                last_revive_tick = router.ticks
                if rebalance_after_revive:
                    router.rebalance()
                if audit_rounds:
                    # one recovery round settles: arrivals pause, the
                    # cluster drains to quiet, both audits run, then
                    # the workload resumes against the healed topology
                    drain_quiet()
                    audit(f'post-revive-{sid}')
        n_arrive = min(arrivals_per_tick, requests - submitted)
        for _ in range(max(0, n_arrive)):
            t = tenant_names[rng.randrange(tenants)]
            w = writers[t]
            roll = rng.random()
            if roll < subscribe_fraction:
                aux.append(router.submit(t, 'subscribe'))
                submitted += 1
            elif roll < subscribe_fraction + sync_fraction:
                aux.append(router.submit(t, 'sync', None))
                submitted += 1
            else:
                if w.inflight is not None:
                    continue             # writer busy: seq order first
                payload = w.next_payload(rng)
                ticket = router.submit(t, 'apply', payload)
                w.inflight = (ticket, payload)
                submitted += 1
        pump()
        harvest()
    drained = drain_quiet(budget=2400)
    fixed_point = None
    if ctrl is not None:
        # the convergence hold: pump 10 quiet decision windows with no
        # arrivals — a converged controller makes ZERO further
        # decisions (an oscillating one keeps hunting and fails here)
        before = len(ctrl.decision_log())
        for _ in range(10 * control_window):
            pump()
        harvest()
        fixed_point = len(ctrl.decision_log()) == before
    elapsed = time.perf_counter() - start   # serving window: audits are
    final = audit('final')                  # verification, not serving

    health = shard_stats()
    link_stats = {}
    for (src, dst), link in router._links.items():
        if link is not None:
            link_stats[f'{src}->{dst}'] = dict(link.stats)
    report = {
        'leg': name,
        'shards': n_shards,
        'tenants': tenants,
        'requests_offered': requests,
        'submitted': submitted,
        'completed_ok': counts['ok'],
        'rejections': {k: v for k, v in sorted(counts.items())
                       if k != 'ok'},
        'untyped_escapes': untyped,
        'elapsed_s': round(elapsed, 3),
        'ticks': router.ticks,
        'requests_per_s': round(counts['ok'] / elapsed, 1)
        if elapsed else None,
        'lease_ticks': lease_ticks,
        'paced': bool(pace),
        'ticks_slipped': slipped if pace else None,
        'ticks_slipped_per_shard': {sid: router.shards[sid].ticks_slipped
                                    for sid in shard_ids} if pace else None,
        'scrub_mismatches': len(router.scrub_mismatches),
        'kills': len(mttrs),
        'failovers': len(router.failovers),
        'mttr_ticks': [m['mttr_ticks'] for m in mttrs],
        'drained': drained,
        'audits': audits,
        'final_audit': final,
        'shard_health_delta': {k: health[k] - base_health.get(k, 0)
                               for k in health
                               if health[k] != base_health.get(k, 0)},
        'link_stats': link_stats,
    }
    ok = (untyped == 0 and final['acked_lost'] == 0 and
          final['replica_mismatches'] == 0 and
          all(a['acked_lost'] == 0 and a['replica_mismatches'] == 0
              for a in audits) and drained)
    if mttr_bound is not None:
        ok = ok and all(m['mttr_ticks'] is not None and
                        m['mttr_ticks'] <= mttr_bound
                        for m in mttrs if m['tenants'])
    if ctrl is not None:
        gauges = ctrl.gauges()
        per_policy = {}
        for (policy, _action, _mode), n in gauges['decisions'].items():
            per_policy[policy] = per_policy.get(policy, 0) + n
        last_tick = gauges['last_decision_tick']
        settle = None
        if last_revive_tick is not None and last_tick is not None and \
                last_tick > last_revive_tick:
            settle = last_tick - last_revive_tick
        report['control'] = {
            'mode': control,
            'window': control_window,
            'windows': gauges['windows'],
            'decisions': per_policy,
            'actuations': sum(
                n for (_p, _a, mode), n in gauges['decisions'].items()
                if mode == 'active'),
            'reversals': gauges['reversals'],
            'last_decision_tick': last_tick,
            'last_revive_tick': last_revive_tick,
            'settle_ticks': settle,
            'fixed_point': fixed_point,
            'decide_s_max': gauges['decide_s_max'],
            'ledger_entries': len(ctrl.decision_log()),
        }
        # the anti-oscillation bound: a policy flip-flopping on one
        # target more than twice in an episode is hunting, not
        # converging — and the post-drain hold must be decision-free
        ok = ok and all(n <= 2 for n in gauges['reversals'].values())
        ok = ok and fixed_point
        if settle_bound is not None and last_revive_tick is not None:
            ok = ok and (settle is None or settle <= settle_bound)
    report['ok'] = ok
    router.close()
    return report


def run_standard_legs(sessions=1000, tenants=64, requests=10_000,
                      seed=0, exact_device=False, sync_fraction=0.25):
    """The three standing legs: clean, chaos, 2x overload."""
    legs = []
    legs.append(run_leg('clean', sessions=sessions, tenants=tenants,
                        requests=requests, seed=seed,
                        sync_fraction=sync_fraction,
                        exact_device=exact_device))
    legs.append(run_leg('chaos', sessions=sessions, tenants=tenants,
                        requests=requests, chaos=True, seed=seed + 1,
                        sync_fraction=sync_fraction,
                        exact_device=exact_device))
    legs.append(run_leg('overload', sessions=sessions, tenants=tenants,
                        requests=requests, overload=True, seed=seed + 2,
                        sync_fraction=sync_fraction,
                        exact_device=exact_device))
    return legs


def run_tier_leg(name='tier_hybrid', *, docs=512, hot=48, rounds=30,
                 writes_per_round=24, seed=0, budget_docs=None,
                 stage_schedule=None, path=None):
    """Hybrid live/parked storage-tier leg (ISSUE-15 acceptance): a
    hot-skewed write stream over a doc population living under a
    RESIDENT-BYTES ceiling, with the cost-based tiering plane doing ALL
    demotion — zero manual ``park`` calls — fed by the round-17 memory
    watermarks (``fleet_resident_bytes``). Parked docs that take writes
    revive through the engine (live/parked churn -> arena garbage ->
    cost-model vacuums), and a brownout stage schedule (stage 2 mid-leg
    by default) runs the model's defer/fire ledger, flight-recorded.

    Final CONVERGENCE AUDIT: every doc — live or parked — must be
    byte-identical to a control fleet fed exactly the committed
    changes (parked docs compare their canonical chunk bytes; no
    revive). Returns the leg report dict; ``ok`` summarizes."""
    import shutil
    import tempfile
    from automerge_tpu.fleet.backend import init_docs
    from automerge_tpu.fleet.storage import StorageEngine
    from automerge_tpu.fleet.tiering import (ClockDemote, CostModel,
                                             TieringController,
                                             tiering_stats)
    from automerge_tpu.observability.perf import sample_watermarks

    rng = random.Random(seed)
    root = path or tempfile.mkdtemp(prefix='loadgen-tier-')
    own_root = path is None
    fleet = DocFleet()
    eng = StorageEngine(fleet, path=os.path.join(root, 'arena'))

    # the demote signal: LIVE (unfrozen) docs. The fleet's device grids
    # are capacity-sized (fleet_resident_bytes cannot fall when a doc
    # parks — only a capacity shrink moves it), so the leg budgets the
    # per-doc HOST cost directly: live-doc count against a doc budget,
    # with the byte watermarks sampled into the report for the record.
    def resident():
        return sum(1 for h in by_doc.values()
                   if h is not None and not h.get('frozen'))

    handles = init_docs(docs, fleet)
    ledger = [[] for _ in range(docs)]       # committed changes per doc
    seqs = [0] * docs
    by_doc = {d: handles[d] for d in range(docs)}   # live handle or None
    parked_id = [None] * docs

    def write_round(targets):
        per_handle, hs = [], []
        for d in targets:
            seqs[d] += 1
            heads = fleet_backend.get_heads(by_doc[d])
            buf = encode_change({
                'actor': f'{d:04x}' * 4, 'seq': seqs[d],
                'startOp': seqs[d], 'time': 0, 'message': '',
                'deps': heads,
                'ops': [{'action': 'set', 'obj': '_root',
                         'key': f'k{seqs[d] % 4}', 'value': d * 100 + seqs[d],
                         'datatype': 'int', 'pred': []}]})
            ledger[d].append(buf)
            per_handle.append([buf])
            hs.append(by_doc[d])
        out, _ = fleet_backend.apply_changes_docs(hs, per_handle,
                                                  mirror=False)
        for d, h in zip(targets, out):
            by_doc[d] = h
        return out

    # seed every doc with one change so parked chunks are non-trivial
    write_round(list(range(docs)))
    if budget_docs is None:
        budget_docs = max(hot * 2, docs // 4)
    budget = budget_docs
    policy = ClockDemote(eng, budget_bytes=budget,
                         source=resident, batch=64)
    # the seam returns FRESH handle dicts each apply (the old ones
    # freeze): register the post-write handles, and re-register after
    # every round below — stale ring entries prune themselves
    policy.register(list(by_doc.values()))
    # an eager model at leg scale: revive-discard garbage pays for a
    # rewrite quickly at stage 0, while the stage-2 write penalty defers
    # it — both verdicts land in the flight record over one leg
    ctrl = TieringController(engine=eng, demote=policy,
                             model=CostModel(min_garbage_bytes=1024,
                                             garbage_byte_cost=8.0))
    t0 = dict(tiering_stats())
    if stage_schedule is None:
        stage_schedule = [0] * (rounds // 3) + [2] * (rounds // 3) + \
            [0] * (rounds - 2 * (rounds // 3))

    pressures = []
    revived = 0
    for r in range(rounds):
        # hot-skewed target draw: 80% hot set, 20% tail
        targets = sorted({
            rng.randrange(hot) if rng.random() < 0.8
            else rng.randrange(docs) for _ in range(writes_per_round)})
        # revive any parked targets through the engine (hybrid churn)
        need = [d for d in targets if by_doc[d] is None]
        if need:
            got = eng.revive([parked_id[d] for d in need])
            revived += len(need)
            for d, h in zip(need, got):
                by_doc[d] = h
                parked_id[d] = None
            policy.register(got)
        out = write_round(targets)
        policy.register(out)
        policy.touch(out)
        stage = stage_schedule[min(r, len(stage_schedule) - 1)]
        ctrl.tick(stage=stage)
        # fold the tick's parks back into the doc map (handle -> id
        # pairs from the clock, so a later write can revive by id)
        if policy.last_parked:
            doc_of = {id(h): d for d, h in by_doc.items()
                      if h is not None}
            for h, i in policy.last_parked:
                d = doc_of.get(id(h))
                if d is not None:
                    by_doc[d] = None
                    parked_id[d] = i
        pressures.append(policy.pressure())

    # ---- convergence audit: control fleet fed exactly the ledger ----
    control_fleet = DocFleet()
    control = init_docs(docs, control_fleet)
    control, _ = fleet_backend.apply_changes_docs(
        control, [list(l) for l in ledger], mirror=False)
    mismatches = 0
    for d in range(docs):
        want = bytes(control[d]['state'].save())
        if by_doc[d] is not None:
            got = bytes(by_doc[d]['state'].save())
        elif parked_id[d] is not None:
            got = bytes(eng.chunk(parked_id[d]))
        else:
            mismatches += 1
            continue
        if got != want:
            mismatches += 1
    t1 = dict(tiering_stats())
    final_pressure = policy.pressure()
    marks = sample_watermarks()
    report = {
        'leg': name, 'docs': docs, 'rounds': rounds,
        'watermarks': {k: marks.get(k, 0) for k in
                       ('rss', 'mainstore_bytes', 'mainstore_disk_bytes')},
        'demoted': t1['tiering_demoted_docs'] - t0['tiering_demoted_docs'],
        'model_vacuums': t1['tiering_vacuums'] - t0['tiering_vacuums'],
        'engine_vacuums': eng.vacuums,
        'deferred': t1['tiering_deferred'] - t0['tiering_deferred'],
        'revived': revived,
        'manual_parks': 0,
        'budget_bytes': budget,
        'final_pressure': round(final_pressure, 3),
        'max_late_pressure': round(max(pressures[rounds // 2:]), 3),
        'audit_mismatches': mismatches,
        'parked_final': len(eng.main),
    }
    report['ok'] = mismatches == 0 and report['demoted'] > 0 and \
        final_pressure <= 1.05
    eng.close()
    if own_root:
        shutil.rmtree(root, ignore_errors=True)
    return report


def run_tier_kill_leg(name='tier_kill', *, docs=32, seed=0, path=None):
    """Kill-driven vacuum leg: a CHILD process parks a doc population
    on the mmap arena, discards a slice, and hard-dies (os._exit)
    INSIDE the vacuum's manifest swap; the parent recovers the arena
    via StorageEngine.open and audits every surviving doc byte-for-byte
    against the child's pre-kill expectations."""
    import shutil
    import subprocess
    import tempfile
    root = path or tempfile.mkdtemp(prefix='loadgen-tierkill-')
    own_root = path is None
    arena = os.path.join(root, 'arena')
    expect_path = os.path.join(root, 'expect.bin')
    script = f'''
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
from automerge_tpu.columnar import encode_change
from automerge_tpu.fleet import backend as fb
from automerge_tpu.fleet.backend import DocFleet, init_docs
from automerge_tpu.fleet.storage import StorageEngine
fleet = DocFleet()
eng = StorageEngine(fleet, path={arena!r}, vacuum_dead_fraction=None)
handles = init_docs({docs}, fleet)
per = [[encode_change({{'actor': f'{{d:04x}}' * 4, 'seq': 1, 'startOp': 1,
        'time': 0, 'message': '', 'deps': [],
        'ops': [{{'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': d, 'datatype': 'int', 'pred': []}}]}})]
       for d in range({docs})]
handles, _ = fb.apply_changes_docs(handles, per, mirror=False)
saves = [bytes(h['state'].save()) for h in handles]
ids = eng.park(handles)
keep = ids[{docs} // 3:]
import struct
with open({expect_path!r}, 'wb') as f:
    for i in keep:
        f.write(struct.pack('<qI', i, len(saves[i])) + saves[i])
eng.discard(ids[:{docs} // 3])
eng.main.sync()
eng.main._arena.fault_point = 'exit:post_manifest'
eng.vacuum_now()       # never returns
'''
    proc = subprocess.run([sys.executable, '-c', script],
                          capture_output=True, timeout=600)
    report = {'leg': name, 'docs': docs,
              'child_exit': proc.returncode}
    if proc.returncode != 71:
        report['ok'] = False
        report['stderr'] = proc.stderr.decode()[-1000:]
        return report
    import struct
    from automerge_tpu.fleet.storage import StorageEngine
    expect = {}
    with open(expect_path, 'rb') as f:
        while True:
            head = f.read(12)
            if len(head) < 12:
                break
            i, ln = struct.unpack('<qI', head)
            expect[i] = f.read(ln)
    eng = StorageEngine.open(arena)
    mismatches = sum(
        1 for i, want in expect.items()
        if i not in eng._row_of or bytes(eng.chunk(i)) != want)
    missing = sorted(set(eng._row_of) - set(expect))
    report.update(recovered=len(eng._row_of),
                  expected=len(expect),
                  audit_mismatches=mismatches,
                  resurrected=len(missing),
                  ok=mismatches == 0 and not missing and
                  len(eng._row_of) == len(expect))
    eng.close()
    if own_root:
        shutil.rmtree(root, ignore_errors=True)
    return report


def main():
    sessions = int(os.environ.get('LOADGEN_SESSIONS', 1000))
    tenants = int(os.environ.get('LOADGEN_TENANTS', 64))
    requests = int(os.environ.get('LOADGEN_REQUESTS', 10_000))
    seed = int(os.environ.get('LOADGEN_SEED', 0))
    n_shards = int(os.environ.get('LOADGEN_SHARDS', 0))
    if os.environ.get('LOADGEN_TIER'):
        # storage-tier mode: the hybrid auto-demote leg + the
        # kill-mid-vacuum recovery leg (ISSUE-15 acceptance)
        legs = [
            run_tier_leg(docs=int(os.environ.get('LOADGEN_TIER_DOCS',
                                                 512)), seed=seed),
            run_tier_kill_leg(seed=seed + 1),
        ]
        for leg in legs:
            print(json.dumps(leg))
            print(f"# {leg['leg']}: {'OK' if leg['ok'] else 'FAIL'} "
                  f"{leg}", file=sys.stderr)
            if not leg['ok']:
                sys.exit(1)
        return
    if n_shards:
        # multi-shard mode: a clean leg plus a kill-one-shard chaos leg
        # (kill at 1/3 of the arrival window, revive at 2/3).
        # LOADGEN_CONTROL=active|shadow adds the self-driving leg: the
        # same kill schedule with a control.Controller on the pump and
        # the hardcoded post-revive rebalance handed to its heal lane.
        arrivals = 8
        window = max(1, requests // arrivals)
        legs = [
            run_shard_leg('shard_clean', n_shards=n_shards,
                          tenants=tenants, requests=requests, seed=seed),
            run_shard_leg('shard_kill', n_shards=n_shards,
                          tenants=tenants, requests=requests,
                          chaos=True, seed=seed + 1,
                          kills=((window // 3, 0, 2 * window // 3),)),
        ]
        control_mode = os.environ.get('LOADGEN_CONTROL')
        if control_mode:
            legs.append(run_shard_leg(
                'shard_control', n_shards=n_shards, tenants=tenants,
                requests=requests, chaos=True, seed=seed + 2,
                kills=((window // 3, 0, 2 * window // 3),),
                control=control_mode, settle_bound=400))
        for leg in legs:
            print(json.dumps(leg))
            ctl = leg.get('control')
            ctl_s = (f", control {ctl['decisions']} decisions "
                     f"{ctl['reversals']} reversals "
                     f"settle {ctl['settle_ticks']} ticks") if ctl else ''
            print(f"# {leg['leg']}: {leg['completed_ok']}/"
                  f"{leg['submitted']} ok, {leg['failovers']} failovers, "
                  f"mttr {leg['mttr_ticks']} ticks, audit "
                  f"{leg['final_audit']}{ctl_s}, "
                  f"{'OK' if leg['ok'] else 'FAIL'}", file=sys.stderr)
            if not leg['ok']:
                sys.exit(1)
        return
    for leg in run_standard_legs(sessions=sessions, tenants=tenants,
                                 requests=requests, seed=seed):
        print(json.dumps(leg))
        audit = leg.get('slo_audit')
        # a SKIPPED audit (tickets still pending at max_ticks) fails the
        # leg like a mismatch would — same contract the test harness's
        # assert_leg_ok enforces; silently passing it would mask a hung
        # or backlogged leg
        ok = leg['untyped_escapes'] == 0 and (
            leg['convergence'] is None or
            leg['convergence']['edit_mismatches'] == 0) and (
            audit is None or ('mismatches' in audit
                              and not audit['mismatches']))
        print(f"# {leg['leg']}: {leg['completed_ok']}/{leg['submitted']} "
              f"ok, p99 {leg['p99_ms']}ms, {leg['rounds_per_s']} rounds/s, "
              f"stage {leg['brownout_stage_final']}, "
              f"{'OK' if ok else 'FAIL'}", file=sys.stderr)
        if not ok:
            sys.exit(1)


if __name__ == '__main__':
    main()
