"""Noise-aware perf regression gate over the bench ledger.

Judges a HEAD bench row against the ledger's trailing same-box history
using the repo's own measurement discipline (BASELINE.md "SLO
contract", BENCH_r07 notes): thresholds derive from RECORDED REP
SPREAD — the paired-interleaved rep samples a bench section records —
never from single-run medians, because this box's unpaired run-to-run
medians swing ±40% (BENCH_r07: 708847 → 415181 with a same-day 486581
control; the "regression" was load) while within-run rep spread is a
few percent.

Per metric, the gate:

1. picks the trailing ``--window`` same-box ledger rows that carry it;
2. derives a noise threshold as the MAX of the available spread
   estimates — pooled rep spread (robust IQR/median over every
   recorded rep list, scaled by ``k``) and cross-round spread (MAD/
   median over the baseline rows' values) — floored at ``--floor``;
3. judges the head value against the baseline median: a rate metric
   (``*_rate``, ``*per_s``, ``*rps``) regresses when it drops more
   than the threshold; a latency metric (``*_ms``, ``*_s``, ``*_us``,
   ``*p50*``/``p99``) regresses when it RISES more than the threshold;
4. refuses to judge at all (verdict ``insufficient``) when there is
   neither rep spread nor >= 3 baseline values — a single unpaired
   median is exactly the artifact this tool exists to retire.

``--check`` is the self-test the bench ``regress`` section runs: a
synthetic ledger built from the RECORDED noise history (rep-level
deltas from BENCH_r11's paired pairs, run-level deltas from
BENCH_r07's swing) must stay QUIET across 5 clean paired head rows and
must FLAG a 1.3x slowdown injected into one seam — both in one
process, exit 0 iff both hold.

Usage:
    python tools/perf_gate.py [--ledger PATH] [--head ROW.json]
                              [--window N] [--floor FRACTION] [--k K]
    python tools/perf_gate.py --check
    python tools/perf_gate.py --render     # trajectory passthrough
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench_ledger  # noqa: E402

# metric-name direction heuristics (shared with the ledger's keys)
_RATE_HINTS = ('_rate', 'per_s', 'rps', '_speedup', 'docs_per_s')
_LATENCY_HINTS = ('_ms', '_us', '_s', 'p50', 'p99', 'mttr')

# Recorded noise history, cited not invented:
# - REP_DELTAS: BENCH_r11_slo.json slo_pair_deltas_s (paired
#   alternating-order leg deltas, seconds) over ~11.7 s legs — the
#   measured WITHIN-RUN spread of this box, rel ~±6%.
# - RUN_DELTAS: BENCH_r07 vs r06 vs same-day control vs thread sweep —
#   the measured BETWEEN-RUN swing, rel ~±40% (the history that
#   repeatedly blamed the box).
REP_REL_DELTAS = [0.42 / 11.7, 0.04 / 11.7, -0.55 / 11.7, -0.18 / 11.7,
                  0.23 / 11.7, -0.11 / 11.7, -0.71 / 11.7, -0.73 / 11.7,
                  0.66 / 11.7, -0.26 / 11.7, 0.38 / 11.7]
RUN_VALUES = [708847.0, 415181.0, 486581.0, 505387.0, 517576.0,
              415767.0]


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return None
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def _rel_iqr(values):
    """Robust relative spread: IQR / median (None when degenerate)."""
    med = _median(values)
    if not med:
        return None
    xs = sorted(values)
    n = len(xs)
    if n < 3:
        return None
    q1 = xs[max(0, (n - 1) // 4)]
    q3 = xs[min(n - 1, (3 * (n - 1) + 2) // 4)]
    return abs((q3 - q1) / med)


def _rel_mad(values):
    med = _median(values)
    if not med:
        return None
    mad = _median([abs(v - med) for v in values])
    return abs(mad / med) if mad is not None else None


def direction(metric):
    m = metric.lower()
    if any(h in m for h in _RATE_HINTS):
        return 'rate'
    if any(h in m for h in _LATENCY_HINTS):
        return 'latency'
    return None


def judge(head, rows, metrics=None, window=8, floor_pct=0.10, k=4.0):
    """Judge ``head`` (a ledger row) against trailing history. Returns
    {'ok', 'regressions', 'findings': [...]} — see the module
    docstring for the rules."""
    box_id = (head.get('box') or {}).get('box_id')
    history = [r for r in rows
               if r is not head and r.get('metrics')
               and ((r.get('box') or {}).get('box_id') == box_id
                    or box_id is None)]
    # NO cross-box fallback: a new/changed box has no honest baseline
    # (the fingerprint contract — an 8-core replacement must never be
    # judged against the 2-core history), so every metric reads
    # `insufficient` until this box banks its own rows.
    head_metrics = head.get('metrics', {})
    names = metrics if metrics is not None else sorted(head_metrics)
    findings = []
    for name in names:
        sense = direction(name)
        value = head_metrics.get(name)
        if sense is None or value is None:
            continue
        base_rows = [r for r in history if name in r['metrics']][-window:]
        base_values = [r['metrics'][name] for r in base_rows]
        # every recorded rep list for this metric, head + history: the
        # judged value is a MEDIAN of reps, so its sampling noise is the
        # pooled rep spread shrunk by sqrt(reps) — the paired-interleaved
        # discipline's whole advantage over unpaired run medians
        rep_spreads = []
        rep_counts = []
        for r in [head] + base_rows:
            reps = (r.get('reps') or {}).get(name)
            if reps and len(reps) >= 3:
                spread = _rel_iqr(reps)
                if spread is not None:
                    rep_spreads.append(spread)
                    rep_counts.append(len(reps))
        hist_spread = _rel_mad(base_values) if len(base_values) >= 3 \
            else None
        if not rep_spreads and hist_spread is None:
            findings.append({'metric': name, 'verdict': 'insufficient',
                             'head': value,
                             'baseline_n': len(base_values)})
            continue
        threshold = floor_pct
        if rep_spreads:
            pooled = _median(rep_spreads)
            n_reps = _median(rep_counts)
            threshold = max(threshold, k * pooled / (n_reps ** 0.5))
        if hist_spread is not None:
            threshold = max(threshold, 1.5 * hist_spread)
        baseline = _median(base_values) if base_values else None
        if baseline is None or baseline == 0:
            findings.append({'metric': name, 'verdict': 'insufficient',
                             'head': value, 'baseline_n': 0})
            continue
        delta = (value - baseline) / baseline
        worse = -delta if sense == 'rate' else delta
        verdict = 'ok'
        if worse > threshold:
            verdict = 'regression'
        elif worse < -threshold:
            verdict = 'improvement'
        findings.append({'metric': name, 'verdict': verdict,
                         'head': value, 'baseline': baseline,
                         'delta_pct': round(delta * 100.0, 2),
                         'threshold_pct': round(threshold * 100.0, 2),
                         'baseline_n': len(base_values),
                         'sense': sense})
    regressions = [f for f in findings if f['verdict'] == 'regression']
    return {'ok': not regressions, 'regressions': regressions,
            'findings': findings}


def render_verdict(result, out=None):
    out = out if out is not None else sys.stdout
    for f in result['findings']:
        if f['verdict'] == 'insufficient':
            print(f'  {f["metric"]:<34} insufficient history '
                  f'(n={f.get("baseline_n", 0)}, no rep spread) — '
                  f'not judged', file=out)
            continue
        arrow = {'ok': ' ', 'improvement': '+', 'regression': '!'}
        print(f'{arrow[f["verdict"]]} {f["metric"]:<34} '
              f'head {f["head"]:.5g} vs baseline {f["baseline"]:.5g} '
              f'({f["delta_pct"]:+.1f}%, noise gate '
              f'±{f["threshold_pct"]:.1f}%, n={f["baseline_n"]}) '
              f'{f["verdict"].upper() if f["verdict"] != "ok" else ""}',
              file=out)
    print(f'# gate: {"OK" if result["ok"] else "REGRESSION"} '
          f'({len(result["findings"])} metric(s) examined, '
          f'{len(result["regressions"])} regression(s))', file=out)


# ---- the --check self-test -------------------------------------------------

def _synthetic_rows(base=700000.0, n_rows=8, reps_per_row=5, offset=0):
    """A synthetic same-box ledger whose rows carry rep lists sampled
    (deterministically) from the RECORDED rep-delta history (the
    BENCH_r11 paired deltas), plus a run-to-run placement term at 1.5x
    that spread — the noise model of a DISCIPLINED paired-section
    history. (The ±40% RUN_VALUES swing is what the unpaired snapshots
    this ledger retires measured; replaying it is the drift detector's
    test, tests/test_perf_obs.py, where per-window aggregation earns
    the immunity.)"""
    box = bench_ledger.box_fingerprint()
    rows = []
    deltas = REP_REL_DELTAS
    for i in range(n_rows):
        run_scale = 1.0 + deltas[(offset + i * 7) % len(deltas)]
        reps = [base * run_scale * (1.0 + deltas[(offset + i * 3 + j) %
                                                 len(deltas)])
                for j in range(reps_per_row)]
        med = _median(reps)
        rows.append(bench_ledger.make_row(
            {'regress_seam_rate': med}, reps={'regress_seam_rate': reps},
            source=f'synthetic:{i}', round_no=i, ts=1.0 + i,
            date='2026-08-04', box=box, sha='synthetic'))
    return rows


def check(out=None):
    """The bench-wired smoke: 5 clean paired head rows must pass
    (ZERO false fires) and a 1.3x slowdown must be flagged. The clean
    heads are judged PAIRED — each head row carries its own rep list
    sampled from the same recorded noise the history carries, which is
    what keeps the ±40% run-level swing out of the verdict."""
    out = out if out is not None else sys.stdout
    rows = _synthetic_rows()
    false_fires = 0
    for trial in range(5):
        head = _synthetic_rows(n_rows=8, offset=trial + 3)[trial % 8]
        head['source'] = f'synthetic:head{trial}'
        result = judge(head, rows, metrics=['regress_seam_rate'])
        fired = not result['ok']
        false_fires += int(fired)
        print(f'# clean paired run {trial + 1}/5: '
              f'{"FIRED (false)" if fired else "quiet"}', file=out)
    slow = _synthetic_rows(n_rows=8, offset=5)[2]
    slow['source'] = 'synthetic:slowdown'
    slow['metrics']['regress_seam_rate'] /= 1.3
    slow['reps']['regress_seam_rate'] = [
        v / 1.3 for v in slow['reps']['regress_seam_rate']]
    result = judge(slow, rows, metrics=['regress_seam_rate'])
    detected = not result['ok']
    print(f'# injected 1.3x slowdown: '
          f'{"DETECTED" if detected else "MISSED"}', file=out)
    ok = false_fires == 0 and detected
    print(f'# perf_gate --check: {"OK" if ok else "FAIL"} '
          f'({false_fires} false fire(s) / 5 clean, slowdown '
          f'{"detected" if detected else "missed"})', file=out)
    return ok


def main(argv):
    ledger_path = None
    head_path = None
    window, floor_pct, k = 8, 0.10, 4.0
    mode = 'judge'
    rest = list(argv)
    while rest:
        arg = rest.pop(0)
        if arg == '--ledger':
            ledger_path = rest.pop(0)
        elif arg == '--head':
            head_path = rest.pop(0)
        elif arg == '--window':
            window = int(rest.pop(0))
        elif arg == '--floor':
            floor_pct = float(rest.pop(0))
        elif arg == '--k':
            k = float(rest.pop(0))
        elif arg == '--check':
            mode = 'check'
        elif arg == '--render':
            mode = 'render'
        else:
            print(__doc__.strip())
            return 2
    if mode == 'check':
        return 0 if check() else 1
    if mode == 'render':
        return bench_ledger.render_trajectory(ledger_path)
    rows, report = bench_ledger.read_rows(ledger_path)
    if report['torn_tail']:
        print('# ledger torn tail skipped', file=sys.stderr)
    if head_path:
        with open(head_path) as f:
            head = json.load(f)
    elif rows:
        head = rows[-1]
        rows = rows[:-1]
    else:
        print('# empty ledger: nothing to judge (run --check for the '
              'self-test)', file=sys.stderr)
        return 2
    result = judge(head, rows, window=window, floor_pct=floor_pct, k=k)
    render_verdict(result)
    return 0 if result['ok'] else 1


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
