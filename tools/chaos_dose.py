"""Offline chaos dose runner (round-5 VERDICT item 7).

Runs the cross-backend chaos differential at the deep-dose knobs
(default 30 seeds x 200 steps x 5 actors — the harness's founders +
mid-run joiners) plus a fleet drop/rebuild-from-logs leg exercising the
donation failure contract (fleet/apply.py: device state is a derived
cache; documents rebuild into a fresh fleet from their change logs),
then writes a summary artifact (default CHAOS_r05.json) so the dose is
reproducible evidence, not a claim.

Usage: python tools/chaos_dose.py [out.json]
Knobs: CHAOS_SEEDS / CHAOS_STEPS / REBUILD_LEGS env vars.
"""

import json
import os
import random
import subprocess
import sys
import time

os.environ['PALLAS_AXON_POOL_IPS'] = ''
os.environ['JAX_PLATFORMS'] = 'cpu'
# The axon site hook may have imported jax at interpreter startup (before
# the env overrides above), so pin the already-imported config too — the
# same trap tests/conftest.py documents; without this the rebuild legs
# hang trying to initialize the tunnel backend.
try:
    import jax
    jax.config.update('jax_platforms', 'cpu')
except ImportError:
    pass
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

SEEDS = int(os.environ.get('CHAOS_SEEDS', '30'))
STEPS = int(os.environ.get('CHAOS_STEPS', '200'))
REBUILD_LEGS = int(os.environ.get('REBUILD_LEGS', '10'))
OUT = sys.argv[1] if len(sys.argv) > 1 else 'CHAOS_r05.json'
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


CHUNK = int(os.environ.get('CHAOS_CHUNK', '5'))


def run_differential():
    """Run the dose as fresh pytest processes of CHUNK seeds each: one
    long-lived process accumulating 30 seeds of XLA CPU compile cache has
    segfaulted the compiler mid-dose (seen at seed 7 of a 30x200 run);
    per-chunk process isolation makes the dose crash-proof and resumable."""
    t0 = time.time()
    chunks = []
    for base in range(0, SEEDS, CHUNK):
        n = min(CHUNK, SEEDS - base)
        env = dict(os.environ, CHAOS_SEEDS=str(n), CHAOS_STEPS=str(STEPS),
                   CHAOS_SEED_BASE=str(base))
        try:
            proc = subprocess.run(
                [sys.executable, '-m', 'pytest', 'tests/test_chaos.py', '-q',
                 '--tb=line', '-p', 'no:cacheprovider'],
                env=env, cwd=ROOT, capture_output=True, text=True,
                timeout=2 * 3600)
            rc = proc.returncode
            tail = (proc.stdout.strip().splitlines() or [''])[-1]
        except subprocess.TimeoutExpired:
            # a hung chunk must not discard the completed chunks' records
            rc, tail = -1, 'TIMEOUT after 2h'
        chunks.append({'seed_base': base, 'seeds': n,
                       'passed': rc == 0,
                       'returncode': rc, 'pytest_tail': tail})
        print(f'chunk seeds {base}..{base + n - 1}: '
              f'{"pass" if rc == 0 else f"FAIL rc={rc}"} '
              f'({tail})', flush=True)
    return {
        'seeds': SEEDS, 'steps': STEPS,
        'actors': '3 founders + 2 mid-run joiners (5)',
        'universes': ['host', 'fleet-lww', 'fleet-exact'],
        'mid_run_device_loss_rebuild': 'every fleet universe, step STEPS//2',
        'passed': all(c['passed'] for c in chunks),
        'chunks': chunks,
        'elapsed_s': round(time.time() - t0, 1),
    }


def run_rebuild_legs():
    sys.path.insert(0, ROOT)
    import automerge_tpu as A
    from automerge_tpu.fleet.backend import (
        DocFleet, init_docs, apply_changes_docs, materialize_docs,
        rebuild_docs)

    alpha = 'abcdefghij'
    mismatches = 0
    t0 = time.time()
    for seed in range(REBUILD_LEGS):
        rng = random.Random(1000 + seed)
        a1, a2 = '11' * 8, 'ee' * 8
        d1 = A.change(A.init(a1), {'time': 0},
                      lambda r: r.update({'t': A.Text('ab'), 'm': {},
                                          'cnt': A.Counter(0)}))
        d2 = A.merge(A.init(a2), d1)
        for step in range(40):
            which = rng.random()
            src = d1 if rng.random() < 0.5 else d2

            def edit(r, rng=rng):
                roll = rng.random()
                if roll < 0.3:
                    r[rng.choice(alpha)] = rng.randrange(100)
                elif roll < 0.5:
                    r['t'].insert_at(rng.randrange(len(r['t']) + 1),
                                     rng.choice(alpha))
                elif roll < 0.7:
                    r['m'][rng.choice(alpha)] = rng.choice(
                        ['s', 1.5, True, None])
                elif roll < 0.85 and 'cnt' in r and \
                        hasattr(r['cnt'], 'increment'):
                    r['cnt'].increment(rng.randrange(-2, 5))
                else:
                    k = rng.choice(alpha)
                    if k in r:
                        del r[k]   # never t/m/c: alpha keys only
            if src is d1:
                d1 = A.change(d1, {'time': 0}, edit)
            else:
                d2 = A.change(d2, {'time': 0}, edit)
            if which < 0.2:
                d1 = A.merge(d1, d2)
            elif which > 0.9:
                d2 = A.merge(d2, d1)
        final = A.merge(A.clone(d1), d2)
        changes = [bytes(b) for b in A.get_all_changes(final)]
        cut = len(changes) // 2
        fleet = DocFleet(doc_capacity=4, key_capacity=64)
        handles = init_docs(2, fleet)
        handles, _ = apply_changes_docs(
            handles, [changes[:cut], changes[:cut]], mirror=False)
        # drop the device: rebuild BOTH docs into a fresh fleet from logs
        rebuilt = rebuild_docs(handles, DocFleet(doc_capacity=4,
                                                 key_capacity=64))
        rebuilt, _ = apply_changes_docs(
            rebuilt, [changes[cut:], changes[cut:]], mirror=False)
        want = dict(final)
        got = materialize_docs(rebuilt)
        from automerge_tpu.backend import get_heads
        from automerge_tpu import frontend as F
        want_heads = get_heads(F.get_backend_state(final, 'dose'))
        for g, h in zip(got, rebuilt):
            if g != want or h['heads'] != want_heads:
                mismatches += 1
    return {'legs': REBUILD_LEGS, 'edits_per_leg': 40,
            'mismatches': mismatches,
            'elapsed_s': round(time.time() - t0, 1)}


def main():
    out = {
        'round': 5,
        'differential': run_differential(),
        'fleet_drop_rebuild': run_rebuild_legs(),
    }
    out['ok'] = out['differential']['passed'] and \
        out['fleet_drop_rebuild']['mismatches'] == 0
    with open(os.path.join(ROOT, OUT), 'w') as f:
        json.dump(out, f, indent=2)
        f.write('\n')
    print(json.dumps(out, indent=2))


if __name__ == '__main__':
    main()
