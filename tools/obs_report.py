"""Phase-attribution report from a host-span Chrome trace (or a flight
recorder forensic dump), and the cross-peer trace stitcher.

Usage:
    python tools/obs_report.py traces/obs_host_trace.json
    python tools/obs_report.py --flight flight-quarantine-1.json
    python tools/obs_report.py --flight after.json before.json
    python tools/obs_report.py --stitch peer_a.json peer_b.json \\
                               [-o stitched_trace.json]
    python tools/obs_report.py --stitch shard0=a.json shard1=b.json
    python tools/obs_report.py --metrics metrics_snapshot.prom
    python tools/archlint.py --check --json - | \\
                               python tools/obs_report.py --archlint -
    python tools/obs_report.py --floor kernel_ledger.json [trace.json]
    python tools/obs_report.py --trajectory [BENCH_LEDGER.jsonl]
    python tools/obs_report.py --control control_ledger.json [--json]
    python tools/obs_report.py --control flight-quarantine-1.json

Floor mode renders the RESIDUAL-FLOOR table the ROADMAP used to carry
as a hand-measured note: per device-kernel-kind dispatch counts,
blocking wall time, and XLA cost_analysis (flops / bytes accessed /
achieved GB/s) from a ``perf.dump_ledger`` JSON, beside the host
phases of an optional span trace — so "native parse vs scatter
dispatch vs host phases" reads from live data
(``observability.perf.instrument_kernel`` wraps every jitted entry
point; the bench ``perf`` section writes the ledger dump).

Metrics mode reads a Prometheus exposition page (a MetricsExporter
``write_snapshot`` file or a curl'd /metrics body) and surfaces the
shard-labeled operational counters — per-shard slipped ticks (the
tick-overrun telemetry: which failure domain's pump does not fit the
serving cadence) and pump seconds — plus any non-zero health counters.

Trace mode reads the Chrome trace-event JSON that
``observability.export_chrome_trace`` writes (a bare event list or a
``{"traceEvents": [...]}`` wrapper — the same shapes Perfetto accepts)
and renders, per span name: call count, total/mean/max milliseconds, and
share of the trace's wall-clock — the per-phase merge-cost breakdown the
ROADMAP's parse/merge-overlap work needs (cf. the differential-merge
phase analysis in PAPERS.md "Fast Updates on Read-Optimized Databases").
Spans nest (native_parse inside turbo_parse, dispatch_grid inside
turbo_dispatch), so percentages legitimately sum past 100; the
``turbo_*`` phase rows tile each batch and sum to ~the batch wall.

Flight mode pretty-prints a forensic dump: trigger, per-doc errors
(slot, durable id, stage, typed error), then the surrounding event ring.
With a second (baseline) dump, the health counters print as the DELTA
between the two dumps — the counter twin of the histogram delta, so two
forensic snapshots bracket an incident the way two bench snapshots
bracket a workload.

Control mode renders the control plane's why-did-it-act timeline from
a ``Controller.dump_decisions`` ledger or a flight dump's
control_decision events: per decision the tick, policy/action/target,
direction, applied/shadow/refused flag, the input signal snapshot that
justified it, and the trace ids of affected in-flight requests —
reversals flagged inline. ``--json`` keeps stdout a single
machine-readable JSON object (the ``--archlint -`` pipe discipline),
and ``-`` reads either payload shape from stdin.

Stitch mode merges span exports from MULTIPLE peers — Chrome traces
(export_chrome_trace) or flight dumps (their ``recent_spans``) — into
ONE Perfetto-loadable trace: each input renders as its own named
process, each file's clock is rebased to its own start (perf_counter
epochs do not align across processes), and spans that share a
``trace``/``links`` id are reported so a request minted on one peer can
be followed into the other peer's generate/receive span tree. Inputs
may be ``shard0=path.json`` to label each process track with its shard
id, and any input whose span ring wrapped (a restarted shard exports a
partial window) has its truncation DISCLOSED in the report — trace ids
stay continuous across the gap, so a failover still stitches.

stdlib only — usable on a box with nothing else installed (the counter
delta helper is loaded straight from
automerge_tpu/observability/metrics.py by file path, which keeps one
implementation without importing the package).
"""

import importlib.util
import json
import os
import sys


def _metrics_mod():
    """observability/metrics.py loaded by path (stdlib importlib only):
    the shared counts_delta without pulling the package import chain."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'automerge_tpu', 'observability',
        'metrics.py')
    spec = importlib.util.spec_from_file_location('_obs_metrics', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_events(path, phases=('X',)):
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        if 'traceEvents' in data:
            data = data.get('traceEvents', [])
        elif 'recent_spans' in data:
            # a flight dump: its span tail in iter_spans() shape
            data = [{'ph': 'X', 'name': s['name'],
                     'ts': s['t0_ns'] / 1000.0,
                     'dur': s['dur_ns'] / 1000.0,
                     'tid': s.get('tid', 0) % 1_000_000,
                     'args': s.get('attrs') or {}}
                    for s in data['recent_spans']]
        else:
            data = []
    return [e for e in data if e.get('ph') in phases]


def _union(intervals):
    """Total µs covered by the union of (lo, hi) intervals."""
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in sorted(intervals):
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def attribution(events):
    """Per-name rollup: count, cpu (summed durations), wall (union of the
    name's intervals — with the multi-core parse, spans of one name run
    CONCURRENTLY on pool workers, so cpu > wall measures parallelism),
    mean/max duration (µs), wall share. Returns (rows sorted by cpu desc,
    wall_us)."""
    stats = {}
    ivs = {}
    lo, hi = None, None
    for e in events:
        name = e.get('name', '?')
        dur = float(e.get('dur', 0.0))
        ts = float(e.get('ts', 0.0))
        ent = stats.setdefault(name, [0, 0.0, 0.0])
        ent[0] += 1
        ent[1] += dur
        if dur > ent[2]:
            ent[2] = dur
        ivs.setdefault(name, []).append((ts, ts + dur))
        lo = ts if lo is None else min(lo, ts)
        hi = ts + dur if hi is None else max(hi, ts + dur)
    wall = (hi - lo) if events else 0.0
    # % wall from the UNION, not the cpu sum: concurrent same-name spans
    # (pool workers) would otherwise print shares past 100%
    rows = [(name, n, tot, _union(ivs[name]), tot / n, mx,
             (100.0 * _union(ivs[name]) / wall) if wall else 0.0)
            for name, (n, tot, mx) in stats.items()]
    rows.sort(key=lambda r: -r[2])
    return rows, wall


def render_trace(path, out=None):
    events = load_events(path)
    rows, wall = attribution(events)
    print(f'# {path}: {len(events)} spans, wall {wall / 1000.0:.2f} ms',
          file=out)
    print(f'{"phase":<24}{"calls":>7}{"cpu ms":>10}{"wall ms":>10}'
          f'{"par":>6}{"mean ms":>10}{"max ms":>10}{"% wall":>8}', file=out)
    for name, n, tot, wall_n, mean, mx, pct in rows:
        par = tot / wall_n if wall_n else 1.0
        print(f'{name:<24}{n:>7}{tot / 1000.0:>10.3f}'
              f'{wall_n / 1000.0:>10.3f}{par:>6.2f}'
              f'{mean / 1000.0:>10.3f}{mx / 1000.0:>10.3f}{pct:>8.1f}',
              file=out)
    # Pool view: per-slice parse spans carry worker/chunk attrs; cpu/wall
    # over them is the measured pool parallelism, and occupancy relates
    # that to the configured lane count when the spans recorded it.
    chunk = [e for e in events if e.get('name') == 'parse_chunk']
    if chunk:
        cpu = sum(float(e.get('dur', 0.0)) for e in chunk)
        wall_c = _union([(float(e['ts']), float(e['ts']) + float(e['dur']))
                         for e in chunk])
        workers = {(e.get('args') or {}).get('worker') for e in chunk}
        lanes = [e for e in events if e.get('name') == 'native_parse']
        threads = max(((e.get('args') or {}).get('threads') or 0)
                      for e in lanes) if lanes else len(workers)
        occ = (100.0 * cpu / (wall_c * threads)) if wall_c and threads \
            else 0.0
        print(f'# parse pool: {len(chunk)} slices over {len(workers)} '
              f'workers, cpu {cpu / 1000.0:.3f} ms / wall '
              f'{wall_c / 1000.0:.3f} ms = {cpu / wall_c if wall_c else 1:.2f}x '
              f'parallel, occupancy {occ:.0f}% of {threads} lanes', file=out)
    return rows


def _event_trace_ids(event):
    """Trace ids an event references: its own ``trace`` attr plus any
    batch-span ``links`` (the fused-dispatch -> member-request edges)."""
    args = event.get('args') or {}
    ids = set()
    if args.get('trace'):
        ids.add(args['trace'])
    for link in args.get('links') or ():
        ids.add(link)
    return ids


def _split_labeled(arg):
    """A stitch input may be ``shardname=path`` (the shard label a
    multi-shard deployment names its exports by) or a bare path (the
    basename then labels the process). Only treat ``lhs=`` as a label
    when the whole arg isn't itself an existing file (paths may contain
    '=')."""
    if '=' in arg and not os.path.exists(arg):
        label, _, path = arg.partition('=')
        if label and path:
            return label, path
    return None, arg


def stitch(paths, out_path=None):
    """Merge multiple peers' span exports into one Perfetto trace (see
    the module docstring). Each input may be ``shard=path`` to label its
    process track. Returns (events, shared_trace_ids, truncated) where
    shared ids appear in MORE than one input — the stitched requests —
    and truncated maps labels whose span ring wrapped (a restarted or
    long-lived shard) to their dropped-span counts: the window loss is
    DISCLOSED, and trace ids still correlate across the gap (they ride
    the surviving spans, not the ring indices)."""
    events = []
    ids_by_file = []
    truncated = {}
    seen_labels = set()
    for pid, arg in enumerate(paths, start=1):
        label, path = _split_labeled(arg)
        if label is None:
            label = os.path.basename(path)
        if label in seen_labels:
            # two unlabeled inputs sharing a basename must not merge
            # their process tracks or truncation disclosures
            label = f'{label}#{pid}'
        seen_labels.add(label)
        file_events = load_events(path, phases=('X', 'I'))
        t0 = min((float(e.get('ts', 0.0)) for e in file_events),
                 default=0.0)
        ids = set()
        events.append({'ph': 'M', 'name': 'process_name', 'pid': pid,
                       'tid': 0, 'args': {'name': label}})
        for e in file_events:
            e = dict(e)
            e['pid'] = pid
            # each process's perf_counter epoch is private: rebase every
            # file to its own start so the peers render side by side
            # (cross-host clocks cannot be aligned; the trace ids are
            # the correlation, not the timestamps)
            e['ts'] = float(e.get('ts', 0.0)) - t0
            e.setdefault('tid', 0)
            if e.get('ph') == 'I' and e.get('name') == 'spans_dropped':
                # the export's in-band truncation marker: this ring
                # wrapped (or was restarted) and older spans are gone
                truncated[label] = truncated.get(label, 0) + \
                    int((e.get('args') or {}).get('dropped', 0))
            events.append(e)
            ids |= _event_trace_ids(e)
        ids_by_file.append(ids)
    shared = set()
    for i, ids in enumerate(ids_by_file):
        for other in ids_by_file[i + 1:]:
            shared |= ids & other
    if out_path is not None:
        with open(out_path, 'w') as f:
            json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'},
                      f)
    return events, shared, truncated


def render_stitch(paths, out_path, out=None):
    events, shared, truncated = stitch(paths, out_path)
    spans = [e for e in events if e.get('ph') == 'X']
    print(f'# stitched {len(paths)} peers: {len(spans)} spans'
          f'{" -> " + out_path if out_path else ""}', file=out)
    for label, dropped in sorted(truncated.items()):
        print(f'# shard {label}: span ring truncated ({dropped} older '
              f'spans dropped) — window is partial; trace ids remain '
              f'continuous across the gap', file=out)
    by_trace = {}
    for e in spans:
        for tid in _event_trace_ids(e) & shared:
            by_trace.setdefault(tid, []).append(e)
    for trace_id in sorted(shared):
        rows = by_trace.get(trace_id, [])
        peers = sorted({e['pid'] for e in rows})
        names = sorted({e.get('name', '?') for e in rows})
        print(f'# trace {trace_id}: {len(rows)} spans across peers '
              f'{peers} ({", ".join(names)})', file=out)
    if not shared:
        print('# no trace ids shared across inputs (were the messages '
              'enveloped? generate with trace_ctx=...)', file=out)
    return shared


def render_flight(path, baseline=None, out=None):
    with open(path) as f:
        report = json.load(f)
    print(f'# flight record: trigger={report.get("trigger")!r} '
          f'seq={report.get("seq")}', file=out)
    detail = report.get('detail') or {}
    for err in detail.get('errors', []):
        print(f'  doc {err.get("doc")} (durable id '
              f'{err.get("durable_id")}): {err.get("error")} at stage '
              f'{err.get("stage")!r} — {err.get("message")}', file=out)
    for key in ('torn_tail_bytes', 'rotted_records', 'global_max'):
        if detail.get(key):
            print(f'  {key}: {detail[key]}', file=out)
    events = report.get('events', [])
    print(f'# surrounding events ({len(events)}):', file=out)
    for ev in events:
        kind = ev.get('kind')
        rest = {k: v for k, v in ev.items() if k not in ('kind', 'ts_ns')}
        print(f'  [{kind}] {rest}', file=out)
    spans = report.get('recent_spans', [])
    if spans:
        print(f'# phase timeline around the fault ({len(spans)} spans):',
              file=out)
        for s in spans:
            extra = f' {s["attrs"]}' if s.get('attrs') else ''
            err = f' ERROR={s["error"]}' if s.get('error') else ''
            print(f'  {s["name"]:<22}{s["dur_ns"] / 1e6:9.3f} ms'
                  f'{extra}{err}', file=out)
    health = report.get('health') or {}
    if baseline is not None:
        with open(baseline) as f:
            base_health = json.load(f).get('health') or {}
        moved = {k: v for k, v in _metrics_mod().counts_delta(
            health, base_health).items() if v}
        if moved:
            print(f'# health counters moved since {baseline}: {moved}',
                  file=out)
    else:
        moved = {k: v for k, v in health.items() if v}
        if moved:
            print(f'# health counters at dump: {moved}', file=out)
    return report


def render_metrics(path, out=None):
    """Pretty-print a Prometheus exposition page (a MetricsExporter
    ``write_snapshot`` file, or anything curl'd from /metrics): the
    shard-labeled operational counters first — per-shard slipped ticks
    (tick-overrun telemetry) and pump seconds — then the health-counter
    roll-up, so a shard deployment's cadence health reads at a glance
    without a Prometheus server in the loop."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines()
                 if ln and not ln.startswith('#')]
    slips, pumps, health = [], [], []
    for ln in lines:
        name = ln.split('{', 1)[0].split(' ', 1)[0]
        if name.endswith('shard_ticks_slipped_total'):
            slips.append(ln)
        elif name.endswith('shard_pump_seconds'):
            pumps.append(ln)
        elif name.endswith('health_total'):
            health.append(ln)
    if slips:
        print('# per-shard slipped ticks (pump overran the serving '
              'cadence):', file=out)
        for ln in slips:
            print(f'  {ln}', file=out)
    if pumps:
        print('# per-shard last pump seconds:', file=out)
        for ln in pumps:
            print(f'  {ln}', file=out)
    moved = [ln for ln in health if not ln.rstrip().endswith(' 0')]
    if moved:
        print('# health counters (non-zero):', file=out)
        for ln in moved:
            print(f'  {ln}', file=out)
    if not (slips or pumps or moved):
        print('# no shard telemetry or non-zero health counters in '
              f'{path}', file=out)
    return 0


def render_archlint(path, out=None):
    """Pretty-print an ``archlint --json`` payload (file or ``-`` for
    stdin): the per-rule violation/suppression roll-up, every violation
    with its file:line, and the justified suppressions — the static-
    contract counterpart of the runtime health-counter report."""
    if path == '-':
        data = json.load(sys.stdin)
    else:
        with open(path) as f:
            data = json.load(f)
    if data.get('version') != 1:
        print(f'unsupported archlint payload version '
              f'{data.get("version")!r}', file=sys.stderr)
        return 2
    per_rule = {}
    for f in data.get('findings', []):
        bucket = 'suppressed' if f.get('suppressed') else 'violations'
        per_rule.setdefault(f['rule'], {'violations': 0,
                                        'suppressed': 0})[bucket] += 1
    print(f'# archlint over {data.get("files")} files: '
          f'{data.get("violations")} violations, '
          f'{data.get("suppressed")} suppressed '
          f'({data.get("unlisted")} unlisted, '
          f'{len(data.get("stale", []))} stale baseline entries)',
          file=out)
    for rule in data.get('rules', []):
        rid = rule['id']
        counts = per_rule.get(rid, {'violations': 0, 'suppressed': 0})
        print(f'  {rid:20s} {counts["violations"]:3d} violations  '
              f'{counts["suppressed"]:3d} suppressed', file=out)
    for f in data.get('findings', []):
        if not f.get('suppressed'):
            print(f'  VIOLATION {f["path"]}:{f["line"]}: [{f["rule"]}] '
                  f'{f["message"]}', file=out)
    for f in data.get('findings', []):
        if f.get('suppressed'):
            print(f'  suppressed {f["path"]}:{f["line"]} [{f["rule"]}]: '
                  f'{f.get("justification")}', file=out)
    for e in data.get('stale', []):
        print(f'  STALE baseline entry {e.get("fingerprint")} '
              f'[{e.get("rule")}] {e.get("path")}', file=out)
    for e in data.get('errors', []):
        print(f'  UNPARSEABLE {e.get("path")}: {e.get("message")}',
              file=out)
    return 0 if not (data.get('violations') or data.get('unlisted') or
                     data.get('stale') or data.get('errors')) else 1


def _control_payload(path):
    """Load a ``--control`` input: a ``Controller.dump_decisions``
    ledger, a flight-recorder dump (its control_decision events), or
    ``-`` for either on stdin. Returns (decisions, gauges, mode)."""
    if path == '-':
        data = json.load(sys.stdin)
    else:
        with open(path) as f:
            data = json.load(f)
    if data.get('kind') == 'control_ledger':
        return (data.get('decisions', []), data.get('gauges', {}),
                data.get('mode'))
    if 'events' in data:         # a dump_flight_record forensic report
        decisions = [e for e in data['events']
                     if e.get('kind') == 'control_decision']
        return decisions, {}, None
    raise ValueError(
        f'{path}: neither a control ledger (kind=control_ledger) nor '
        f'a flight dump (events=[...])')


def render_control(path, json_out=False, out=None):
    """The why-did-it-act timeline: every control-plane decision with
    the signal snapshot that justified it and the trace ids of the
    in-flight requests it touched. With ``json_out`` the report is a
    single machine-readable JSON object on stdout (the ``--archlint
    --json -`` pipe discipline: nothing else lands on stdout)."""
    try:
        decisions, gauges, mode = _control_payload(path)
    except (ValueError, KeyError) as exc:
        print(f'unsupported --control payload: {exc}', file=sys.stderr)
        return 2
    per_policy = {}
    reversals = {}
    applied = shadow = 0
    for d in decisions:
        key = (d.get('policy', '?'), d.get('action', '?'))
        per_policy[key] = per_policy.get(key, 0) + 1
        if d.get('reversal'):
            pol = d.get('policy', '?')
            reversals[pol] = reversals.get(pol, 0) + 1
        if d.get('mode') == 'shadow':
            shadow += 1
        elif d.get('applied'):
            applied += 1
    if json_out:
        report = {'kind': 'control_report', 'mode': mode,
                  'decisions': len(decisions), 'applied': applied,
                  'shadow': shadow,
                  'per_policy': {f'{p}/{a}': n
                                 for (p, a), n in sorted(per_policy.items())},
                  'reversals': reversals, 'gauges': gauges,
                  'timeline': decisions}
        json.dump(report, sys.stdout, indent=1, default=repr)
        sys.stdout.write('\n')
        return 0 if decisions or gauges else 1
    out = out if out is not None else sys.stdout
    mode_s = f' mode={mode}' if mode else ''
    print(f'# control plane: {len(decisions)} decisions'
          f' ({applied} applied, {shadow} shadow,'
          f' {sum(reversals.values())} reversals){mode_s}', file=out)
    for (pol, act), n in sorted(per_policy.items()):
        rev = reversals.get(pol, 0)
        print(f'  {pol:<16} {act:<16} {n:3d} decisions'
              + (f'  {rev} reversals' if rev else ''), file=out)
    if gauges:
        print(f'# windows={gauges.get("windows")} '
              f'ticks={gauges.get("ticks")} '
              f'last_decision_tick={gauges.get("last_decision_tick")} '
              f'decide_s_last={gauges.get("decide_s_last", 0):.6f} '
              f'decide_s_max={gauges.get("decide_s_max", 0):.6f}',
              file=out)
        active = gauges.get('active') or {}
        for key, value in sorted(active.items(), key=repr):
            print(f'  active {key}: {value}', file=out)
    if decisions:
        print('# timeline (oldest first):', file=out)
    for d in decisions:
        flags = []
        if d.get('mode') == 'shadow':
            flags.append('SHADOW')
        elif d.get('applied'):
            flags.append('applied')
        else:
            flags.append('REFUSED')
        if d.get('reversal'):
            flags.append('REVERSAL')
        head = (f'  tick {d.get("tick", "?"):>6} '
                f'{d.get("policy", "?")}/{d.get("action", "?")} '
                f'{d.get("target", "")} '
                f'dir={d.get("direction", "")} [{" ".join(flags)}]')
        print(head, file=out)
        if d.get('detail'):
            print(f'    why: {d["detail"]}', file=out)
        sig = d.get('signals') or {}
        adm = sig.get('admission') or {}
        bits = []
        if adm:
            bits.append(f'reject_frac={adm.get("reject_frac", 0):.3f} '
                        f'queue={adm.get("queue_pressure", 0):.3f}')
        ten = sig.get('tenant') or {}
        if ten:
            bits.append(f'tenant admitted_d={ten.get("admitted_d")} '
                        f'throttled_d={ten.get("throttled_d")} '
                        f'rate={ten.get("rate")}')
        wm = (sig.get('watermark') or {}).get('pressure')
        if wm is not None:
            bits.append(f'watermark={wm:.3f}')
        if 'pump_mean_s' in sig:
            bits.append(f'pump_mean_s={sig["pump_mean_s"]:.6f} '
                        f'misplaced={len(sig.get("misplaced", ()))}')
        if bits:
            print(f'    signals: {"; ".join(bits)}', file=out)
        traces = d.get('traces') or []
        if traces:
            print(f'    traces: {", ".join(str(t) for t in traces)}',
                  file=out)
    if not decisions:
        print('# no control decisions in the window '
              '(a quiet controller is a converged controller)', file=out)
    return 0


def render_floor(ledger_path, trace_path=None, out=None):
    """The residual-floor table: device kernels (cost ledger) and,
    when a trace is given, the host phases they compete with."""
    out = out if out is not None else sys.stdout
    with open(ledger_path) as f:
        dump = json.load(f)
    kernels = dump.get('kernels', {})
    print(f'# device-kernel cost ledger ({ledger_path}):', file=out)
    if not kernels:
        print('  (no dispatches recorded — was the ledger enabled? '
              'perf.enable_ledger() / enable_observatory())', file=out)
    else:
        # "host ms" = host-blocking wall (execution on the sync CPU
        # backend; enqueue time on async devices — perf.py caveat)
        print(f'  {"kernel":<30}{"disp":>6}{"host ms":>10}'
              f'{"ms/disp":>9}{"MFLOP":>9}{"MB acc":>9}{"GB/s":>7}',
              file=out)
        rows = sorted(kernels.items(),
                      key=lambda kv: -kv[1].get('seconds', 0.0))
        for kind, row in rows:
            disp = row.get('dispatches', 0)
            wall = row.get('seconds', 0.0) * 1000.0
            flops = row.get('flops_total')
            acc = row.get('bytes_accessed_total')
            gbs = row.get('gbytes_per_s')
            print(f'  {kind:<30}{disp:>6}{wall:>10.2f}'
                  f'{wall / max(disp, 1):>9.3f}'
                  f'{(flops or 0) / 1e6:>9.2f}'
                  f'{(acc or 0) / 1e6:>9.2f}'
                  f'{gbs if gbs is not None else 0:>7.2f}', file=out)
        errors = [(kind, sig['cost']['error'])
                  for kind, row in kernels.items()
                  for sig in row.get('signatures', ())
                  if 'error' in (sig.get('cost') or {})]
        for kind, err in errors:
            print(f'  # {kind}: cost_analysis unavailable ({err})',
                  file=out)
    if trace_path:
        print(f'# host phases beside them ({trace_path}):', file=out)
        events = load_events(trace_path)
        rows, wall = attribution(events)
        for name, n, tot, wall_n, mean, mx, pct in rows[:12]:
            print(f'  {name:<30}{n:>6}{tot / 1000.0:>10.2f} ms cpu '
                  f'({pct:>5.1f}% of wall)', file=out)
    mem = dump.get('watermarks')
    if mem:
        print('# memory watermarks (bytes, current / high):', file=out)
        for tier in sorted(mem.get('current', {})):
            cur = mem['current'][tier]
            high = mem.get('high', {}).get(tier, cur)
            print(f'  {tier:<30}{cur:>14,} / {high:,}', file=out)
    return 0


def main(argv):
    if not argv or argv[0] in ('-h', '--help'):
        print(__doc__.strip())
        return 2
    if argv[0] == '--floor':
        if len(argv) < 2:
            print('--floor needs a kernel-ledger JSON path '
                  '(perf.dump_ledger / bench perf section)',
                  file=sys.stderr)
            return 2
        return render_floor(argv[1], argv[2] if len(argv) > 2 else None)
    if argv[0] == '--trajectory':
        # the bench-ledger trajectory, from the observability front door
        # (implementation lives in tools/bench_ledger.py)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import bench_ledger
        return bench_ledger.render_trajectory(
            argv[1] if len(argv) > 1 else None)
    if argv[0] == '--archlint':
        if len(argv) < 2:
            print('--archlint needs an `archlint --json` payload path '
                  '(or - for stdin)', file=sys.stderr)
            return 2
        return render_archlint(argv[1])
    if argv[0] == '--control':
        rest = [a for a in argv[1:] if a != '--json']
        json_out = '--json' in argv[1:]
        if not rest:
            print('--control needs a control-ledger JSON '
                  '(Controller.dump_decisions), a flight dump, '
                  'or - for stdin', file=sys.stderr)
            return 2
        return render_control(rest[0], json_out=json_out)
    if argv[0] == '--metrics':
        if len(argv) < 2:
            print('--metrics needs an exposition-file path',
                  file=sys.stderr)
            return 2
        return render_metrics(argv[1])
    if argv[0] == '--flight':
        if len(argv) < 2:
            print('--flight needs a dump path', file=sys.stderr)
            return 2
        render_flight(argv[1], baseline=argv[2] if len(argv) > 2 else None)
        return 0
    if argv[0] == '--stitch':
        paths = []
        out_path = 'stitched_trace.json'
        rest = argv[1:]
        while rest:
            arg = rest.pop(0)
            if arg == '-o':
                if not rest:
                    print('-o needs a path', file=sys.stderr)
                    return 2
                out_path = rest.pop(0)
            else:
                paths.append(arg)
        if len(paths) < 2:
            print('--stitch needs at least two exports', file=sys.stderr)
            return 2
        render_stitch(paths, out_path)
        return 0
    render_trace(argv[0])
    return 0


if __name__ == '__main__':
    try:
        sys.exit(main(sys.argv[1:]))
    except BrokenPipeError:      # | head
        os_devnull = open('/dev/null', 'w')
        sys.stdout = os_devnull
        sys.exit(0)
