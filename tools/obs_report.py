"""Phase-attribution report from a host-span Chrome trace (or a flight
recorder forensic dump).

Usage:
    python tools/obs_report.py traces/obs_host_trace.json
    python tools/obs_report.py --flight flight-quarantine-1.json

Trace mode reads the Chrome trace-event JSON that
``observability.export_chrome_trace`` writes (a bare event list or a
``{"traceEvents": [...]}`` wrapper — the same shapes Perfetto accepts)
and renders, per span name: call count, total/mean/max milliseconds, and
share of the trace's wall-clock — the per-phase merge-cost breakdown the
ROADMAP's parse/merge-overlap work needs (cf. the differential-merge
phase analysis in PAPERS.md "Fast Updates on Read-Optimized Databases").
Spans nest (native_parse inside turbo_parse, dispatch_grid inside
turbo_dispatch), so percentages legitimately sum past 100; the
``turbo_*`` phase rows tile each batch and sum to ~the batch wall.

Flight mode pretty-prints a forensic dump: trigger, per-doc errors
(slot, durable id, stage, typed error), then the surrounding event ring.

stdlib only — usable on a box with nothing else installed.
"""

import json
import sys


def load_events(path):
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get('traceEvents', [])
    return [e for e in data if e.get('ph') == 'X']


def attribution(events):
    """Per-name rollup: count, total/mean/max duration (µs), wall share.
    Returns (rows sorted by total desc, wall_us)."""
    stats = {}
    lo, hi = None, None
    for e in events:
        name = e.get('name', '?')
        dur = float(e.get('dur', 0.0))
        ts = float(e.get('ts', 0.0))
        ent = stats.setdefault(name, [0, 0.0, 0.0])
        ent[0] += 1
        ent[1] += dur
        if dur > ent[2]:
            ent[2] = dur
        lo = ts if lo is None else min(lo, ts)
        hi = ts + dur if hi is None else max(hi, ts + dur)
    wall = (hi - lo) if events else 0.0
    rows = [(name, n, tot, tot / n, mx,
             (100.0 * tot / wall) if wall else 0.0)
            for name, (n, tot, mx) in stats.items()]
    rows.sort(key=lambda r: -r[2])
    return rows, wall


def render_trace(path, out=sys.stdout):
    events = load_events(path)
    rows, wall = attribution(events)
    print(f'# {path}: {len(events)} spans, wall {wall / 1000.0:.2f} ms',
          file=out)
    print(f'{"phase":<24}{"calls":>7}{"total ms":>11}{"mean ms":>10}'
          f'{"max ms":>10}{"% wall":>8}', file=out)
    for name, n, tot, mean, mx, pct in rows:
        print(f'{name:<24}{n:>7}{tot / 1000.0:>11.3f}'
              f'{mean / 1000.0:>10.3f}{mx / 1000.0:>10.3f}{pct:>8.1f}',
              file=out)
    return rows


def render_flight(path, out=sys.stdout):
    with open(path) as f:
        report = json.load(f)
    print(f'# flight record: trigger={report.get("trigger")!r} '
          f'seq={report.get("seq")}', file=out)
    detail = report.get('detail') or {}
    for err in detail.get('errors', []):
        print(f'  doc {err.get("doc")} (durable id '
              f'{err.get("durable_id")}): {err.get("error")} at stage '
              f'{err.get("stage")!r} — {err.get("message")}', file=out)
    for key in ('torn_tail_bytes', 'rotted_records', 'global_max'):
        if detail.get(key):
            print(f'  {key}: {detail[key]}', file=out)
    events = report.get('events', [])
    print(f'# surrounding events ({len(events)}):', file=out)
    for ev in events:
        kind = ev.get('kind')
        rest = {k: v for k, v in ev.items() if k not in ('kind', 'ts_ns')}
        print(f'  [{kind}] {rest}', file=out)
    spans = report.get('recent_spans', [])
    if spans:
        print(f'# phase timeline around the fault ({len(spans)} spans):',
              file=out)
        for s in spans:
            extra = f' {s["attrs"]}' if s.get('attrs') else ''
            err = f' ERROR={s["error"]}' if s.get('error') else ''
            print(f'  {s["name"]:<22}{s["dur_ns"] / 1e6:9.3f} ms'
                  f'{extra}{err}', file=out)
    health = report.get('health') or {}
    moved = {k: v for k, v in health.items() if v}
    if moved:
        print(f'# health counters at dump: {moved}', file=out)
    return report


def main(argv):
    if not argv or argv[0] in ('-h', '--help'):
        print(__doc__.strip())
        return 2
    if argv[0] == '--flight':
        if len(argv) < 2:
            print('--flight needs a dump path', file=sys.stderr)
            return 2
        render_flight(argv[1])
        return 0
    render_trace(argv[0])
    return 0


if __name__ == '__main__':
    try:
        sys.exit(main(sys.argv[1:]))
    except BrokenPipeError:      # | head
        os_devnull = open('/dev/null', 'w')
        sys.stdout = os_devnull
        sys.exit(0)
