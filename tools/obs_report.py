"""Phase-attribution report from a host-span Chrome trace (or a flight
recorder forensic dump).

Usage:
    python tools/obs_report.py traces/obs_host_trace.json
    python tools/obs_report.py --flight flight-quarantine-1.json

Trace mode reads the Chrome trace-event JSON that
``observability.export_chrome_trace`` writes (a bare event list or a
``{"traceEvents": [...]}`` wrapper — the same shapes Perfetto accepts)
and renders, per span name: call count, total/mean/max milliseconds, and
share of the trace's wall-clock — the per-phase merge-cost breakdown the
ROADMAP's parse/merge-overlap work needs (cf. the differential-merge
phase analysis in PAPERS.md "Fast Updates on Read-Optimized Databases").
Spans nest (native_parse inside turbo_parse, dispatch_grid inside
turbo_dispatch), so percentages legitimately sum past 100; the
``turbo_*`` phase rows tile each batch and sum to ~the batch wall.

Flight mode pretty-prints a forensic dump: trigger, per-doc errors
(slot, durable id, stage, typed error), then the surrounding event ring.

stdlib only — usable on a box with nothing else installed.
"""

import json
import sys


def load_events(path):
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get('traceEvents', [])
    return [e for e in data if e.get('ph') == 'X']


def _union(intervals):
    """Total µs covered by the union of (lo, hi) intervals."""
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in sorted(intervals):
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def attribution(events):
    """Per-name rollup: count, cpu (summed durations), wall (union of the
    name's intervals — with the multi-core parse, spans of one name run
    CONCURRENTLY on pool workers, so cpu > wall measures parallelism),
    mean/max duration (µs), wall share. Returns (rows sorted by cpu desc,
    wall_us)."""
    stats = {}
    ivs = {}
    lo, hi = None, None
    for e in events:
        name = e.get('name', '?')
        dur = float(e.get('dur', 0.0))
        ts = float(e.get('ts', 0.0))
        ent = stats.setdefault(name, [0, 0.0, 0.0])
        ent[0] += 1
        ent[1] += dur
        if dur > ent[2]:
            ent[2] = dur
        ivs.setdefault(name, []).append((ts, ts + dur))
        lo = ts if lo is None else min(lo, ts)
        hi = ts + dur if hi is None else max(hi, ts + dur)
    wall = (hi - lo) if events else 0.0
    # % wall from the UNION, not the cpu sum: concurrent same-name spans
    # (pool workers) would otherwise print shares past 100%
    rows = [(name, n, tot, _union(ivs[name]), tot / n, mx,
             (100.0 * _union(ivs[name]) / wall) if wall else 0.0)
            for name, (n, tot, mx) in stats.items()]
    rows.sort(key=lambda r: -r[2])
    return rows, wall


def render_trace(path, out=sys.stdout):
    events = load_events(path)
    rows, wall = attribution(events)
    print(f'# {path}: {len(events)} spans, wall {wall / 1000.0:.2f} ms',
          file=out)
    print(f'{"phase":<24}{"calls":>7}{"cpu ms":>10}{"wall ms":>10}'
          f'{"par":>6}{"mean ms":>10}{"max ms":>10}{"% wall":>8}', file=out)
    for name, n, tot, wall_n, mean, mx, pct in rows:
        par = tot / wall_n if wall_n else 1.0
        print(f'{name:<24}{n:>7}{tot / 1000.0:>10.3f}'
              f'{wall_n / 1000.0:>10.3f}{par:>6.2f}'
              f'{mean / 1000.0:>10.3f}{mx / 1000.0:>10.3f}{pct:>8.1f}',
              file=out)
    # Pool view: per-slice parse spans carry worker/chunk attrs; cpu/wall
    # over them is the measured pool parallelism, and occupancy relates
    # that to the configured lane count when the spans recorded it.
    chunk = [e for e in events if e.get('name') == 'parse_chunk']
    if chunk:
        cpu = sum(float(e.get('dur', 0.0)) for e in chunk)
        wall_c = _union([(float(e['ts']), float(e['ts']) + float(e['dur']))
                         for e in chunk])
        workers = {(e.get('args') or {}).get('worker') for e in chunk}
        lanes = [e for e in events if e.get('name') == 'native_parse']
        threads = max(((e.get('args') or {}).get('threads') or 0)
                      for e in lanes) if lanes else len(workers)
        occ = (100.0 * cpu / (wall_c * threads)) if wall_c and threads \
            else 0.0
        print(f'# parse pool: {len(chunk)} slices over {len(workers)} '
              f'workers, cpu {cpu / 1000.0:.3f} ms / wall '
              f'{wall_c / 1000.0:.3f} ms = {cpu / wall_c if wall_c else 1:.2f}x '
              f'parallel, occupancy {occ:.0f}% of {threads} lanes', file=out)
    return rows


def render_flight(path, out=sys.stdout):
    with open(path) as f:
        report = json.load(f)
    print(f'# flight record: trigger={report.get("trigger")!r} '
          f'seq={report.get("seq")}', file=out)
    detail = report.get('detail') or {}
    for err in detail.get('errors', []):
        print(f'  doc {err.get("doc")} (durable id '
              f'{err.get("durable_id")}): {err.get("error")} at stage '
              f'{err.get("stage")!r} — {err.get("message")}', file=out)
    for key in ('torn_tail_bytes', 'rotted_records', 'global_max'):
        if detail.get(key):
            print(f'  {key}: {detail[key]}', file=out)
    events = report.get('events', [])
    print(f'# surrounding events ({len(events)}):', file=out)
    for ev in events:
        kind = ev.get('kind')
        rest = {k: v for k, v in ev.items() if k not in ('kind', 'ts_ns')}
        print(f'  [{kind}] {rest}', file=out)
    spans = report.get('recent_spans', [])
    if spans:
        print(f'# phase timeline around the fault ({len(spans)} spans):',
              file=out)
        for s in spans:
            extra = f' {s["attrs"]}' if s.get('attrs') else ''
            err = f' ERROR={s["error"]}' if s.get('error') else ''
            print(f'  {s["name"]:<22}{s["dur_ns"] / 1e6:9.3f} ms'
                  f'{extra}{err}', file=out)
    health = report.get('health') or {}
    moved = {k: v for k, v in health.items() if v}
    if moved:
        print(f'# health counters at dump: {moved}', file=out)
    return report


def main(argv):
    if not argv or argv[0] in ('-h', '--help'):
        print(__doc__.strip())
        return 2
    if argv[0] == '--flight':
        if len(argv) < 2:
            print('--flight needs a dump path', file=sys.stderr)
            return 2
        render_flight(argv[1])
        return 0
    render_trace(argv[0])
    return 0


if __name__ == '__main__':
    try:
        sys.exit(main(sys.argv[1:]))
    except BrokenPipeError:      # | head
        os_devnull = open('/dev/null', 'w')
        sys.stdout = os_devnull
        sys.exit(0)
