"""Seeded byte-level fuzzer over every wire decoder.

The failure-containment contract (BASELINE.md) promises that hostile bytes
fed to any decode entry point raise a TYPED error — `MalformedChange`,
`MalformedDocument`, `MalformedSyncMessage` (all `WireCorruption`,
all ValueError) — and never a bare `IndexError`/`KeyError`/
`AssertionError`, a segfault, or a hang. This tool is the enforcement:
build a corpus of VALID wire artifacts (changes, a saved document, a sync
message, native column buffers, Bloom filter bytes), derive hostile
mutants (truncate, splice, bit-flip, byte-set, prefix-garbage), and feed
every mutant to every decoder, recording anything that escapes the typed
envelope.

Targets:
- columnar.decode_change / decode_change_meta / split_containers
- columnar.decode_document (and through it the loader's parked-chunk path)
- backend.sync.decode_sync_message / decode_sync_state
- fleet.loader.load_docs (native document parse + install, per-doc
  fallback) — must return handles or raise typed, and NEVER poison a
  neighbouring doc in the same batch
- native.decode_rle_column / decode_delta_column / decode_boolean_column
  (the C++ codec's bounds discipline; skipped when the toolchain is absent)
- fleet.bloom.probe_bloom_filters_batch — corrupt filter bytes must
  probe as all-False (containment), never raise
- apply_changes_docs(on_error='quarantine') over a poisoned batch — the
  healthy neighbour doc must commit and read back intact
- fleet.durability frame decoders: parse_journal_bytes (strict mode
  raises only MalformedJournal/TornTail; LENIENT mode must never raise
  at all — recovery consumes the damage report), parse_snapshot_bytes
  and parse_manifest_bytes (typed MalformedSnapshot only)
- query.decode_cursor — the subscription-cursor decode boundary: a
  hostile cursor fails typed InvalidCursor, and one that DECODES must
  round-trip (re-encode to the same bytes: canonical-form discipline)
- fleet.hashindex peer sent-spaces — mutant bytes decode to connect/
  send/probe/disconnect/reset programs over PeerSentSet vs a
  dict-of-sets oracle (differential; reconnects must never inherit a
  predecessor's sent set)

Dose scales like tests/test_chaos.py: FUZZ_SEEDS x FUZZ_CASES mutants per
target (env-overridable); tests/test_fuzz_wire.py runs a small smoke dose
in tier-1, `python tools/fuzz_wire.py` a 10x default dose standalone.
The corpus size lands in the 'fuzz_corpus_size' health counter.
"""

import os
import random
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import automerge_tpu as A                                    # noqa: E402
from automerge_tpu import native                             # noqa: E402
from automerge_tpu.backend.sync import (                     # noqa: E402
    decode_sync_message, decode_sync_state, encode_sync_state,
    init_sync_state)
from automerge_tpu.columnar import (                         # noqa: E402
    decode_change, decode_change_meta, decode_document, encode_change,
    split_containers)
from automerge_tpu.errors import AutomergeError  # noqa: E402
from automerge_tpu.observability import register_health_source   # noqa: E402

# Typed envelope: what a decoder may raise on hostile bytes. InvalidChange
# (causal rules) is included because a mutant can decode into a
# causally-nonsense change; everything else is an escape.
ALLOWED = (AutomergeError,)

_corpus_size = [0]
register_health_source('fuzz_corpus_size', lambda: _corpus_size[0])

HANG_SECONDS = 20


class _Hang(Exception):
    pass


def _alarm(signum, frame):
    raise _Hang(f'decoder exceeded {HANG_SECONDS}s')


def build_corpus():
    """Valid wire artifacts to mutate: binary changes (flat + nested +
    deflated-sized), a saved document, sync messages, a sync state, raw
    column buffers."""
    docs = []
    d = A.init('aa' * 16)
    d = A.change(d, {'time': 0}, lambda r: r.update(
        {'text': A.Text('seed text'), 'list': [1, 2, 3],
         'nested': {'k': 'v'}, 'n': 42}))
    d = A.change(d, {'time': 0}, lambda r: r.update(
        {'big': 'x' * 600, 'f': 2.5, 'b': True}))
    e = A.merge(A.init('bb' * 16), d)
    e = A.change(e, {'time': 0}, lambda r: r.update({'other': 7}))
    d = A.merge(d, e)
    changes = [bytes(c) for c in A.get_all_changes(d)]
    saved = bytes(A.save(d))

    # a second document exercising the extractor's full column surface:
    # counters + incs (succ-synthesized attribution), deletes (del
    # resynthesis from succ), floats/strings-in-lists, deflated columns
    d2 = A.init('cc' * 16)
    d2 = A.change(d2, {'time': 1}, lambda r: r.update(
        {'c': A.Counter(1), 'l': [1, 'two', 3.0], 'pad': 'z' * 700}))
    d2 = A.change(d2, {'time': 2}, lambda r: r['c'].increment(4))

    def drop(r):
        del r['l'][1]
        del r['pad']
    d2 = A.change(d2, {'time': 0}, drop)
    saved2 = bytes(A.save(d2))

    backend = A.Frontend.get_backend_state(d, 'fuzz')
    from automerge_tpu import backend as host
    s1 = init_sync_state()
    _, sync_msg = host.generate_sync_message(backend, s1)
    state_bytes = bytes(encode_sync_state(
        {'sharedHeads': host.get_heads(backend)}))

    from automerge_tpu.backend.sync import BloomFilter
    bloom = BloomFilter([c_meta for c_meta in
                         (host.get_heads(backend) * 4)]).bytes

    # durability artifacts: a CRC-framed journal, a snapshot, a manifest
    import json
    from automerge_tpu.fleet import durability as D
    journal = b''.join(
        [D.encode_frame(D.KIND_INIT, 0, b'')] +
        [D.encode_frame(D.KIND_CHANGE, i % 3, c)
         for i, c in enumerate(changes)] +
        [D.encode_frame(D.KIND_FREE, 2, b'')])
    # the columnar hot-seam format: duplicated crc'd tables + payloads
    journal_batch = D.encode_frame(D.KIND_INIT, 0, b'') + \
        D._encode_batch(list(range(len(changes) * 3)), changes * 3)
    snapshot = D.SNAP_MAGIC + \
        D.encode_frame(D.KIND_DOC, 0, saved) + \
        D.encode_frame(D.KIND_QUEUED, 0, changes[0]) + \
        D.encode_frame(D.KIND_END, 0, D._U32.pack(2))
    manifest = D.MANIFEST_MAGIC + D.encode_frame(
        D.KIND_END, 0, json.dumps(
            {'seq': 3, 'snapshot': 'snapshot-00000003.snap',
             'journal': 'journal-00000003.log', 'journal_offset': 0,
             'next_doc_id': 3}).encode('utf8'))

    # subscription cursors: empty, single-head, and multi-head frontiers
    from automerge_tpu.query import encode_cursor
    cursors = [encode_cursor([]),
               encode_cursor(host.get_heads(backend)),
               encode_cursor(host.get_heads(backend) +
                             ['ab' * 32, 'cd' * 32])]

    # frontier-index / storage-engine trace programs: opaque byte blobs
    # the differential targets interpret as op streams — every mutant is
    # a valid program, so mutation explores the trace space
    import hashlib as _hashlib
    traces = [_hashlib.sha256(f'hashindex-trace-{i}'.encode()).digest() * 6
              for i in range(3)]
    storage_traces = [
        _hashlib.sha256(f'storage-trace-{i}'.encode()).digest() * 4
        for i in range(3)]
    peer_traces = [
        _hashlib.sha256(f'peer-space-trace-{i}'.encode()).digest() * 5
        for i in range(3)]

    corpus = {
        'change': changes,
        'document': [saved, saved2],
        'sync_message': [bytes(sync_msg)],
        'sync_state': [state_bytes],
        'bloom': [bytes(bloom)],
        'column': [bytes(c[12:48]) for c in changes],   # raw column-ish runs
        'journal': [journal, journal_batch],
        'snapshot': [snapshot],
        'manifest': [manifest],
        'cursor': cursors,
        'hashindex_trace': traces,
        'storage_trace': storage_traces,
        'peer_space_trace': peer_traces,
    }
    _corpus_size[0] = sum(len(v) for v in corpus.values())
    return corpus


def mutate(rng, data):
    """One hostile mutant of `data` (possibly multiple stacked faults)."""
    out = bytearray(data)
    for _ in range(rng.randrange(1, 4)):
        roll = rng.random()
        if roll < 0.25 and out:                       # truncate
            del out[rng.randrange(len(out)):]
        elif roll < 0.45 and out:                     # bit flip
            pos = rng.randrange(len(out))
            out[pos] ^= 1 << rng.randrange(8)
        elif roll < 0.60 and out:                     # byte set
            out[rng.randrange(len(out))] = rng.randrange(256)
        elif roll < 0.75:                             # splice garbage
            pos = rng.randrange(len(out) + 1)
            out[pos:pos] = bytes(rng.randrange(256)
                                 for _ in range(rng.randrange(1, 9)))
        elif roll < 0.9 and len(out) > 2:             # cut a window
            a = rng.randrange(len(out))
            b = min(len(out), a + rng.randrange(1, 17))
            del out[a:b]
        else:                                         # duplicate a window
            a = rng.randrange(len(out) + 1)
            out[a:a] = out[:rng.randrange(0, 17)]
    return bytes(out)


def _journal_lenient_target(mutant):
    """The LENIENT journal scan is recovery's parser: it must return a
    (records, damage-report) pair on ANY input — a raise here, even a
    typed one, would make one rotted disk byte fleet-fatal. Re-raise as
    untyped so the fuzz net flags it."""
    from automerge_tpu.fleet.durability import parse_journal_bytes
    try:
        records, info = parse_journal_bytes(mutant)
    except BaseException as exc:
        raise RuntimeError(
            f'lenient journal scan raised {type(exc).__name__}: '
            f'{exc}') from exc
    assert isinstance(records, list) and 'torn_tail_bytes' in info


def _targets():
    """(name, callable(mutant)) pairs. Callables either succeed (a mutant
    may decode to something valid) or raise inside ALLOWED."""
    from automerge_tpu.fleet.durability import (parse_journal_bytes,
                                                parse_manifest_bytes,
                                                parse_snapshot_bytes)
    targets = [
        ('decode_cursor', _cursor_target),
        ('decode_change', decode_change),
        ('decode_change_meta', lambda b: decode_change_meta(b, True)),
        ('split_containers', split_containers),
        ('decode_document', decode_document),
        ('decode_sync_message', decode_sync_message),
        ('decode_sync_state', decode_sync_state),
        ('journal_strict', lambda b: parse_journal_bytes(b, strict=True)),
        ('journal_lenient', _journal_lenient_target),
        ('snapshot_frames', parse_snapshot_bytes),
        ('manifest', parse_manifest_bytes),
    ]
    if native.available():
        targets += [
            ('native_rle', native.decode_rle_column),
            ('native_delta', native.decode_delta_column),
            ('native_boolean', native.decode_boolean_column),
            ('native_extract', _extract_target),
        ]
    return targets


def _extract_target(mutant):
    """The native change-list extractor (delta+main materialize kernel)
    against hostile document chunks. The wrapper NEVER raises — it
    returns per-doc None for anything outside its provable subset — and
    whenever it claims success its output must be byte-identical to the
    Python decode_document + encode_change round trip (which must then
    also succeed): a mutant the extractor accepts but Python rejects
    (or renders differently) is a containment hole, re-raised untyped
    so the fuzz net flags it."""
    out = native.extract_changes([mutant])
    if out is None or out[0] is None:
        return
    chunks, hashes, _max_ops = out[0]
    try:
        decoded = decode_document(mutant)
        py = [bytes(encode_change(ch)) for ch in decoded]
        py_hashes = [ch['hash'] for ch in decoded]
    except BaseException as exc:
        raise RuntimeError(
            f'extractor accepted a doc Python rejects: '
            f'{type(exc).__name__}: {exc}') from exc
    if chunks != py or hashes != py_hashes:
        raise RuntimeError('extractor output diverges from Python '
                           'decode+re-encode on an accepted doc')


def _cursor_target(mutant):
    """The subscription-cursor decode boundary (query engine): hostile
    bytes raise typed InvalidCursor only, and any mutant that decodes
    must re-encode to the same bytes — decode_cursor accepting a
    non-canonical frame would split subscriber equivalence classes."""
    from automerge_tpu.query import decode_cursor, encode_cursor
    heads = decode_cursor(mutant)
    if bytes(encode_cursor(heads)) != bytes(mutant):
        raise RuntimeError('decode_cursor accepted a non-canonical frame')


def _hashindex_target(mutant):
    """Differential fuzz of the frontier index (fleet/hashindex.py): the
    mutant bytes read as a trace program — (op, space, key) byte triples
    — run against BOTH the open-addressing table (tiny capacity, low
    device threshold, so host->device promotion, collision chains, and
    grow-by-migration all fire constantly) and a dict-of-sets oracle.
    Any membership disagreement is raised untyped so the fuzz net flags
    it; a healthy index never raises on ANY byte sequence."""
    import hashlib as _hashlib
    from automerge_tpu.fleet.hashindex import HashIndex
    ix = HashIndex(capacity=8, device_min=24, load_max=0.7)
    oracle, live = {}, []
    data = bytes(mutant)[:180]
    for k in range(0, len(data) - 2, 3):
        op, s, kid = data[k], data[k + 1], data[k + 2]
        if not live or (op % 13 == 0 and len(live) < 6):
            sid = ix.new_space()
            oracle[sid] = set()
            live.append(sid)
        sid = live[s % len(live)]
        key = _hashlib.sha256(bytes([kid])).hexdigest()
        if op % 13 == 1 and len(live) > 1:
            live.remove(sid)
            ix.release_space(sid)
            oracle[sid] = set()
        elif op % 2:
            ix.insert(sid, [key])
            oracle[sid].add(key)
        else:
            got = bool(ix.probe(sid, [key])[0])
            if got != (key in oracle[sid]):
                raise RuntimeError(
                    'hashindex membership diverged from the set oracle')


def _peer_space_target(mutant):
    """Differential fuzz of the peer sent-spaces (fleet/hashindex.py
    PeerSentSet): the mutant bytes read as a trace program — (op, peer,
    key) byte triples decoding to connect / send / probe / disconnect /
    reset(=True) / flush — run against BOTH the shared open-addressing
    table (tiny capacity + low device threshold, so host->device
    promotion, collision chains, and grow-by-migration fire constantly)
    and a dict-of-sets oracle. Checks the fabric's reconnect contract
    too: space ids are never reused, so a peer reconnecting after
    disconnect/reset can never inherit its predecessor's sent set. Any
    divergence raises untyped so the fuzz net flags it; a healthy table
    never raises on ANY byte sequence."""
    import hashlib as _hashlib
    from automerge_tpu.fleet.hashindex import (HashIndex, PeerSentSet,
                                               flush_peer_sets)
    table = HashIndex(capacity=8, device_min=24, load_max=0.7)
    peers, oracle, seen_sids = [], {}, set()

    def connect():
        ps = PeerSentSet(table)
        if ps.sid in seen_sids:
            raise RuntimeError('peer space id reused')
        seen_sids.add(ps.sid)
        peers.append(ps)
        oracle[id(ps)] = set()
        return ps

    connect()
    data = bytes(mutant)[:150]
    for k in range(0, len(data) - 2, 3):
        op, p, kid = data[k] % 16, data[k + 1], data[k + 2]
        ps = peers[p % len(peers)]
        key = _hashlib.sha256(bytes([kid % 24])).hexdigest()
        if op == 0 and len(peers) < 6:                       # connect
            connect()
        elif op == 1 and len(peers) > 1:                     # disconnect
            peers.remove(ps)
            ps.release()
            del oracle[id(ps)]
            if ps.alive or any(ps.contains_many([key])):
                raise RuntimeError('released peer space still answers')
        elif op == 2:                                        # reset=True
            old = ps
            peers.remove(old)
            old.release()
            old_sent = oracle.pop(id(old))
            ps = connect()
            if ps.sid <= old.sid:
                raise RuntimeError('reset reused or rewound a space id')
            hits = ps.contains_many(sorted(old_sent) or [key])
            if any(hits):
                raise RuntimeError(
                    'reconnected peer inherited predecessor sent set')
        elif op == 3:                                        # flush all
            flush_peer_sets(peers)
        elif op % 2:                                         # send
            ps.add(key)
            oracle[id(ps)].add(key)
        else:                                                # probe
            want = key in oracle[id(ps)]
            if (key in ps) != want or \
                    bool(ps.contains_many([key])[0]) != want:
                raise RuntimeError(
                    'peer space membership diverged from the set oracle')
    flush_peer_sets(peers)
    for ps in peers:                                         # final audit
        members = sorted(oracle[id(ps)])
        if members:
            got = ps.contains_many(members)
            if not all(got):
                raise RuntimeError('post-flush membership lost a sent hash')


_storage_corpus = []


def _storage_trace_target(mutant):
    """Differential fuzz of the mmap-backed storage engine (fleet/
    storage.py + fleet/segment.py): the mutant bytes read as a trace
    program — (op, arg) byte pairs driving ingest / discard / read /
    vacuum / crash-reopen against a DISK-backed StorageEngine, checked
    at every step against a plain {id: (bytes, heads)} oracle. The
    reopen step exercises the manifest + CRC frame recovery path mid-
    trace. Any divergence (wrong bytes, wrong heads, id resurrection)
    raises untyped so the fuzz net flags it; a healthy engine never
    raises on ANY byte sequence."""
    import tempfile
    from automerge_tpu.columnar import DocChunkView
    from automerge_tpu.fleet.storage import StorageEngine
    if not _storage_corpus:
        from automerge_tpu.fleet.backend import DocFleet
        chunks = []
        d = A.init('ee' * 16)
        for k in range(4):
            d = A.change(d, {'time': 0}, lambda r: r.update({'k': k}))
            chunks.append(bytes(A.save(d)))
        _storage_corpus.append((chunks, DocFleet()))  # fleet never revives
    chunks, fleet = _storage_corpus[0]
    with tempfile.TemporaryDirectory(prefix='fuzz-arena-') as root:
        path = root + '/store'
        eng = StorageEngine(fleet=fleet, path=path, segment_bytes=1 << 12,
                            vacuum_dead_fraction=0.5)
        oracle = {}
        data = bytes(mutant)[:60]
        for k in range(0, len(data) - 1, 2):
            op, arg = data[k] % 6, data[k + 1]
            live = sorted(oracle)
            if op == 0 or not live:                      # ingest
                chunk = chunks[arg % len(chunks)]
                did = eng.ingest_chunks([chunk])[0]
                if did in oracle:
                    raise RuntimeError('storage id reused while live')
                oracle[did] = (chunk, sorted(DocChunkView(chunk).heads))
            elif op == 1:                                # discard
                did = live[arg % len(live)]
                eng.discard([did])
                del oracle[did]
            elif op in (2, 3):                           # read compare
                did = live[arg % len(live)]
                chunk, heads = oracle[did]
                if bytes(eng.chunk(did)) != chunk:
                    raise RuntimeError('chunk bytes diverged from oracle')
                if eng.heads(did) != heads:
                    raise RuntimeError('heads diverged from oracle')
            elif op == 4:                                # vacuum
                eng.vacuum_now()
            else:                                        # crash + reopen
                eng.main.sync()
                eng.main.close()
                eng = StorageEngine.open(path, fleet=fleet,
                                         segment_bytes=1 << 12)
                if sorted(eng._row_of) != live:
                    raise RuntimeError(
                        f'recovery id set diverged: {sorted(eng._row_of)}'
                        f' != {live}')
                for did, (chunk, heads) in oracle.items():
                    if bytes(eng.chunk(did)) != chunk or \
                            eng.heads(did) != heads:
                        raise RuntimeError('recovery diverged from oracle')
        eng.main.close()


def _probe_bloom_target(mutant):
    """Corrupt filter bytes must probe lenient (all-False), never raise."""
    from automerge_tpu.fleet.bloom import probe_bloom_filters_batch
    hashes = ['ab' * 32, 'cd' * 32]
    probe_bloom_filters_batch([mutant], [hashes])


def _loader_target(corpus):
    """One corrupt + one healthy doc through the batched loader: typed
    containment AND the healthy neighbour must install."""
    from automerge_tpu.fleet.backend import DocFleet, get_heads
    from automerge_tpu.fleet.loader import load_docs

    def run(mutant):
        fleet = DocFleet(doc_capacity=4, key_capacity=64)
        good = corpus['document'][0]
        try:
            handles = load_docs([mutant, good], fleet)
        except ALLOWED:
            return
        assert get_heads(handles[1]), 'healthy doc failed to install'
    return run


def _quarantine_target(corpus):
    """One poisoned + one healthy change batch through the quarantining
    apply: errors stay typed, the neighbour commits."""
    from automerge_tpu.fleet import backend as fb
    from automerge_tpu.fleet.backend import DocFleet, init_docs

    def run(mutant):
        fleet = DocFleet(doc_capacity=4, key_capacity=64)
        handles = init_docs(2, fleet)
        good = corpus['change'][0]
        new_handles, _patches, errors = fb.apply_changes_docs(
            handles, [[mutant], [good]], mirror=False,
            on_error='quarantine')
        if errors[0] is not None:
            assert isinstance(errors[0].error, ALLOWED), errors[0]
        assert errors[1] is None, f'healthy neighbour poisoned: {errors[1]}'
    return run


def run_fuzz(n_seeds=None, n_cases=None, verbose=False):
    """Returns {'cases', 'rejected', 'accepted', 'escaped': [...]} where
    `escaped` lists (target, seed, case, exc_type, message) for anything
    outside the typed envelope — the assertion surface for the tests."""
    n_seeds = n_seeds if n_seeds is not None else \
        int(os.environ.get('FUZZ_SEEDS', '5'))
    n_cases = n_cases if n_cases is not None else \
        int(os.environ.get('FUZZ_CASES', '40'))
    corpus = build_corpus()
    flat_corpus = [(kind, item) for kind, items in corpus.items()
                   for item in items]
    targets = _targets()
    targets.append(('bloom_probe', _probe_bloom_target))
    targets.append(('hashindex_trace', _hashindex_target))
    targets.append(('peer_space_trace', _peer_space_target))
    targets.append(('storage_trace', _storage_trace_target))
    targets.append(('loader_batch', _loader_target(corpus)))
    targets.append(('apply_quarantine', _quarantine_target(corpus)))

    use_alarm = hasattr(signal, 'SIGALRM') and \
        signal.getsignal(signal.SIGALRM) in (signal.SIG_DFL, signal.SIG_IGN,
                                             None, _alarm)
    if use_alarm:
        signal.signal(signal.SIGALRM, _alarm)

    stats = {'cases': 0, 'rejected': 0, 'accepted': 0, 'escaped': []}
    heavy = {'loader_batch', 'apply_quarantine', 'hashindex_trace',
             'peer_space_trace', 'storage_trace'}
    for seed in range(n_seeds):
        rng = random.Random(seed)
        for case in range(n_cases):
            _kind, base = flat_corpus[rng.randrange(len(flat_corpus))]
            mutant = mutate(rng, base)
            for name, fn in targets:
                # the fleet-stack targets are ~100x the decoder cost:
                # run them on a slice of the dose, not every mutant
                if name in heavy and case % 10 != 0:
                    continue
                stats['cases'] += 1
                if use_alarm:
                    signal.alarm(HANG_SECONDS)
                try:
                    fn(mutant)
                    stats['accepted'] += 1
                except ALLOWED:
                    stats['rejected'] += 1
                except Exception as exc:    # noqa: BLE001 - the fuzz net
                    stats['escaped'].append(
                        (name, seed, case, type(exc).__name__, str(exc)[:200]))
                    if verbose:
                        print(f'ESCAPE {name} seed={seed} case={case}: '
                              f'{type(exc).__name__}: {exc}',
                              file=sys.stderr)
                finally:
                    if use_alarm:
                        signal.alarm(0)
    return stats


def main():
    n_seeds = int(os.environ.get('FUZZ_SEEDS', '20'))
    n_cases = int(os.environ.get('FUZZ_CASES', '100'))
    stats = run_fuzz(n_seeds, n_cases, verbose=True)
    print(f"fuzz_wire: {stats['cases']} cases, {stats['rejected']} typed "
          f"rejections, {stats['accepted']} clean decodes, "
          f"{len(stats['escaped'])} escapes")
    if stats['escaped']:
        for row in stats['escaped'][:40]:
            print('  ', row)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
