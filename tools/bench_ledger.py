"""Bench ledger: the repo's perf trajectory as one append-only JSONL.

Every ``bench.py`` run appends ONE row to ``BENCH_LEDGER.jsonl`` at the
repo root (the ``regress`` section does it; ``BENCH_LEDGER=0``
disables, ``BENCH_LEDGER_PATH`` redirects). A row is self-describing:

    {"schema": 1, "ts": ..., "date": "YYYY-MM-DD",
     "source": "bench" | "backfill:BENCH_r07.json",
     "round": 7 | null, "git_sha": "...",
     "box": {"box_id", "cpus", "machine", "python", "platform"},
     "metrics": {"seam_rate": 708847.0, ...},       # flat floats only
     "reps": {"seam_rate": [...]}}                  # per-rep samples,
                                                    # when recorded

``reps`` is what makes the regression gate noise-AWARE: thresholds in
``tools/perf_gate.py`` derive from recorded rep spread, never from a
single-run median (the measurement history's ±40% unpaired swings are
exactly why — BENCH_r07 notes).

Durability contract: ``append_row`` writes one line with a trailing
newline through a single buffered write+flush on an O_APPEND handle —
readers tolerate a TORN TAIL (a crash mid-append leaves a partial last
line, which ``read_rows`` skips and reports rather than dying on), so
the ledger never needs a rewrite cycle and two appenders never corrupt
each other's complete lines.

``backfill`` seeds the ledger from the historical ``BENCH_r*.json``
artifacts (all four generations of their schema), idempotently (a
source file already in the ledger is skipped). ``render_trajectory``
prints the per-round table + sparkline the ROADMAP's "no trajectory
tracking" complaint asks for.

stdlib only (numpy optional) — usable on a box with nothing installed.

Usage:
    python tools/bench_ledger.py --backfill [--ledger PATH]
    python tools/bench_ledger.py --render  [--ledger PATH]
"""

import glob
import hashlib
import json
import os
import platform as _platform
import re
import subprocess
import sys
import time

SCHEMA = 1
LEDGER_NAME = 'BENCH_LEDGER.jsonl'
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the headline + the per-section keys worth tracking across rounds
# (anything else in a row's metrics rides along untracked)
TRAJECTORY_KEYS = (
    'seam_rate', 'seam_commit_rate', 'host_rate',
    'service_clean_rps', 'slo_render_series_per_s',
    'storage_recovery_docs_per_s', 'query_materialize_docs_per_s',
    'shards_rps_4',
)


def default_ledger_path():
    return os.environ.get('BENCH_LEDGER_PATH') or \
        os.path.join(_ROOT, LEDGER_NAME)


def git_sha(root=_ROOT):
    try:
        out = subprocess.run(['git', 'rev-parse', '--short', 'HEAD'],
                             cwd=root, capture_output=True, text=True,
                             timeout=10)
        sha = out.stdout.strip()
        return sha or None
    except Exception:                               # noqa: BLE001
        return None


def box_fingerprint():
    """The box identity rows are grouped by: a same-box baseline means
    a same-fingerprint baseline (an 8-core replacement box must never
    be judged against this 2-core one's numbers)."""
    info = {
        'cpus': os.cpu_count(),
        'machine': _platform.machine(),
        'python': _platform.python_version(),
        'platform': os.environ.get('JAX_PLATFORMS') or 'device',
    }
    digest = hashlib.sha256(
        json.dumps(info, sort_keys=True).encode()).hexdigest()[:12]
    info['box_id'] = digest
    return info


def make_row(metrics, reps=None, source='bench', round_no=None,
             ts=None, date=None, box=None, sha=None, notes=None):
    """Assemble one schema-1 row. ``metrics`` is filtered to finite
    numbers; ``reps`` to lists of finite numbers."""
    clean = {}
    for key, value in (metrics or {}).items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        if value != value or value in (float('inf'), float('-inf')):
            continue
        clean[str(key)] = float(value)
    row = {
        'schema': SCHEMA,
        'ts': float(ts if ts is not None else time.time()),
        'date': date or time.strftime('%Y-%m-%d'),
        'source': source,
        'round': round_no,
        'git_sha': sha if sha is not None else git_sha(),
        'box': box if box is not None else box_fingerprint(),
        'metrics': clean,
    }
    if reps:
        row['reps'] = {str(k): [float(x) for x in v]
                       for k, v in reps.items()
                       if v and all(isinstance(x, (int, float))
                                    and x == x for x in v)}
    if notes:
        row['notes'] = notes
    return row


def append_row(row, path=None):
    """Append one row as one JSONL line. Single write+flush on an
    append-mode handle: complete lines never interleave, and a crash
    mid-write leaves at most one torn tail line that ``read_rows``
    tolerates. Appending AFTER a torn tail first closes the partial
    line with a newline — the torn fragment then reads as one skipped
    corrupt line instead of corrupting the new row too."""
    path = path or default_ledger_path()
    line = json.dumps(row, sort_keys=True) + '\n'
    try:
        with open(path, 'rb') as f:
            f.seek(0, os.SEEK_END)
            if f.tell() > 0:
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b'\n':
                    line = '\n' + line
    except OSError:
        pass
    with open(path, 'a') as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())
    return path


def read_rows(path=None):
    """(rows, report) — every decodable row, oldest first. ``report``
    says what was skipped: ``torn_tail`` (the final line was partial —
    the documented crash-mid-append artifact) and ``corrupt`` (a
    non-final undecodable line, which should never happen and is
    therefore counted loudly rather than hidden)."""
    path = path or default_ledger_path()
    report = {'torn_tail': False, 'corrupt': 0}
    rows = []
    try:
        with open(path) as f:
            raw = f.read()
    except FileNotFoundError:
        return rows, report
    lines = raw.split('\n')
    ends_clean = raw.endswith('\n') or raw == ''
    if ends_clean and lines and lines[-1] == '':
        lines.pop()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1 and not ends_clean:
                report['torn_tail'] = True
            else:
                report['corrupt'] += 1
    return rows, report


# ---- backfill from the historical BENCH_r*.json artifacts ------------------

def _flat_floats(d, out=None):
    """Flatten numeric leaves of a (possibly nested) dict; nested keys
    keep their LEAF name when unique, else 'parent_leaf'."""
    out = {} if out is None else out
    for key, value in d.items():
        if isinstance(value, dict):
            for k2, v2 in value.items():
                if isinstance(v2, (int, float)) and \
                        not isinstance(v2, bool):
                    name = k2 if k2 not in out else f'{key}_{k2}'
                    out[name] = float(v2)
        elif isinstance(value, (int, float)) and \
                not isinstance(value, bool):
            out.setdefault(key, float(value))
    return out


def _parse_bench_artifact(path):
    """One historical BENCH_r*.json -> (metrics, round_no, date). Four
    generations of artifact schema:
    - r01-r07: {'n', 'parsed': {'metric', 'value', ...}, ...}
    - r08/r11/r12: {'round', 'section', 'results': {...}, 'date'}
    - r09/r10: flat {'section', '<key>': float, ...}
    - r13: composite {'round', 'seam': {...}, 'seam_commit': {...}, ...}
    """
    with open(path) as f:
        data = json.load(f)
    name = os.path.basename(path)
    m = re.match(r'BENCH_r(\d+)', name)
    file_round = int(m.group(1)) if m else None
    metrics = {}
    round_no = data.get('round', data.get('n', file_round))
    date = data.get('date')
    if 'parsed' in data and isinstance(data['parsed'], dict):
        parsed = data['parsed']
        if isinstance(parsed.get('value'), (int, float)):
            # the e2e seam headline tracks as seam_rate; anything else
            # (round 1's kernel-only metric) keeps its own name — a
            # 13e9 kernel rate must not pollute the seam trajectory
            key = 'seam_rate' if parsed.get('metric') == \
                'changes_per_sec_backend_seam_e2e' else \
                str(parsed.get('metric') or 'value')
            metrics[key] = float(parsed['value'])
        for key in ('vs_baseline', 'seam_dispatches_per_round',
                    'init_dispatches', 'sync_dispatches_per_round'):
            if isinstance(parsed.get(key), (int, float)):
                metrics[key] = float(parsed[key])
    elif 'results' in data and isinstance(data['results'], dict):
        _flat_floats(data['results'], metrics)
    else:
        # flat section artifact or the composite shape: flatten numeric
        # leaves one level down (composite subsections keep leaf names)
        body = {k: v for k, v in data.items()
                if k not in ('round', 'issue', 'date', 'config', 'notes',
                             'headline')}
        _flat_floats(body, metrics)
        if isinstance(data.get('headline'), dict):
            v = data['headline'].get('seam_rate_changes_per_s')
            if isinstance(v, (int, float)):
                metrics.setdefault('seam_rate', float(v))
    return metrics, round_no, date


def backfill(path=None, root=_ROOT):
    """Append one row per historical BENCH_r*.json not already in the
    ledger (idempotent by source name). Returns the added sources."""
    path = path or default_ledger_path()
    rows, _ = read_rows(path)
    seen = {r.get('source') for r in rows}
    added = []
    for art in sorted(glob.glob(os.path.join(root, 'BENCH_r*.json'))):
        source = f'backfill:{os.path.basename(art)}'
        if source in seen:
            continue
        try:
            metrics, round_no, date = _parse_bench_artifact(art)
        except (OSError, json.JSONDecodeError) as exc:
            print(f'# skip {art}: {exc}', file=sys.stderr)
            continue
        if not metrics:
            print(f'# skip {art}: no numeric metrics', file=sys.stderr)
            continue
        ts = os.path.getmtime(art)
        append_row(make_row(metrics, source=source, round_no=round_no,
                            ts=ts, date=date or
                            time.strftime('%Y-%m-%d',
                                          time.localtime(ts)),
                            sha=None), path)
        added.append(source)
    return added


# ---- trajectory rendering --------------------------------------------------

_BARS = ' .:-=+*#%@'


def _spark(values):
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _BARS[-1] * len(values)
    return ''.join(_BARS[min(int((v - lo) / (hi - lo) *
                                 (len(_BARS) - 1) + 0.5),
                             len(_BARS) - 1)] for v in values)


def render_trajectory(path=None, out=None,
                      keys=TRAJECTORY_KEYS):
    """Per-round table + sparkline over the tracked keys."""
    out = out if out is not None else sys.stdout
    rows, report = read_rows(path)
    if report['torn_tail']:
        print('# ledger has a torn tail line (crash mid-append) — '
              'skipped', file=out)
    if report['corrupt']:
        print(f'# ledger has {report["corrupt"]} corrupt line(s) — '
              f'skipped', file=out)
    if not rows:
        print('# ledger empty (run tools/bench_ledger.py --backfill, '
              'or bench.py regress)', file=out)
        return 0
    rows = sorted(rows, key=lambda r: (r.get('ts') or 0))
    print(f'# {len(rows)} ledger rows, '
          f'{rows[0].get("date")} .. {rows[-1].get("date")}', file=out)
    for key in keys:
        series = [(r.get('round'), r['metrics'][key], r.get('source'))
                  for r in rows if key in r.get('metrics', {})]
        if not series:
            continue
        values = [v for _, v, _ in series]
        newest = series[-1]
        print(f'{key:<32}{_spark(values)}  n={len(values)} '
              f'last={newest[1]:.4g} (round {newest[0]}) '
              f'min={min(values):.4g} max={max(values):.4g}', file=out)
    return 0


def main(argv):
    path = None
    do_backfill = do_render = False
    rest = list(argv)
    while rest:
        arg = rest.pop(0)
        if arg == '--ledger':
            path = rest.pop(0)
        elif arg == '--backfill':
            do_backfill = True
        elif arg == '--render':
            do_render = True
        else:
            print(__doc__.strip())
            return 2
    if not (do_backfill or do_render):
        do_render = True
    if do_backfill:
        added = backfill(path)
        print(f'# backfilled {len(added)} artifact(s): '
              f'{", ".join(a.split(":", 1)[1] for a in added) or "none"}')
    if do_render:
        render_trajectory(path)
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
