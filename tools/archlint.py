#!/usr/bin/env python3
"""archlint: the contract linter CLI (automerge_tpu/analysis).

Usage:
  python tools/archlint.py --check [paths...]     gate mode (CI/tier-1)
  python tools/archlint.py --baseline [paths...]  rewrite the baseline
                                                  from current inline
                                                  suppressions
  python tools/archlint.py --json [FILE|-]        machine output (feeds
                                                  obs_report --archlint)
  python tools/archlint.py --list-rules           show the rule table

Default paths: automerge_tpu/ tools/ bench.py (the whole shipped tree).

--check exits non-zero on: any unsuppressed violation, any inline
suppression not recorded in tools/archlint_baseline.json, any stale
baseline entry. Suppress a line only with
`# archlint: ok[rule-id] <why this is safe>` and re-run --baseline so
the exemption shows up in review as a baseline diff.
"""

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from automerge_tpu import analysis                           # noqa: E402

DEFAULT_PATHS = ('automerge_tpu', 'tools', 'bench.py')
DEFAULT_BASELINE = os.path.join(REPO_ROOT, 'tools',
                                'archlint_baseline.json')


def run(paths, baseline_path, root=None):
    """Lint + baseline check; returns the result dict tests and bench
    consume (counts, findings, stale entries, parse errors)."""
    rules = analysis.get_rules()
    findings, files, errors = analysis.lint_paths(paths, rules, root=root)
    baseline = analysis.load_baseline(baseline_path)
    checked = analysis.check_findings(findings, baseline)
    checked.update({
        'files': files, 'errors': errors, 'findings': findings,
        'baseline_path': baseline_path, 'baseline_size': len(baseline),
        'rules': [{'id': r.rule_id, 'doc': r.doc} for r in rules],
    })
    return checked


def as_json(result):
    return {
        'version': 1,
        'files': len(result['files']),
        'rules': result['rules'],
        'findings': [f.as_dict() for f in result['findings']],
        'violations': len(result['violations']),
        'suppressed': len(result['suppressed']),
        'unlisted': len(result['unlisted']),
        'stale': result['stale'],
        'errors': [{'path': p, 'message': m} for p, m in result['errors']],
        'baseline_size': result['baseline_size'],
    }


def _report(result, out=sys.stdout):
    for f in result['violations']:
        print(f'{f.path}:{f.line}: [{f.rule}] {f.message}', file=out)
    for f in result['unlisted']:
        print(f'{f.path}:{f.line}: [{f.rule}] suppressed inline but '
              f'missing from the baseline — run --baseline and commit '
              f'the diff', file=out)
    for e in result['stale']:
        print(f'{e["path"]}: stale baseline entry {e["fingerprint"]} '
              f'[{e["rule"]}] matches nothing — delete it '
              f'(was: {e["snippet"][:60]!r})', file=out)
    for path, msg in result['errors']:
        print(f'{path}: unparseable: {msg}', file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(prog='archlint', add_help=True)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument('--check', action='store_true',
                      help='gate mode: fail on any new/unlisted/stale')
    mode.add_argument('--baseline', action='store_true',
                      help='rewrite the baseline from inline suppressions')
    mode.add_argument('--list-rules', action='store_true')
    ap.add_argument('--json', metavar='FILE', default=None,
                    help="write machine-readable results ('-' = stdout)")
    ap.add_argument('--baseline-file', default=DEFAULT_BASELINE)
    ap.add_argument('paths', nargs='*', default=None)
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in analysis.get_rules():
            print(f'{rule.rule_id:20s} {rule.doc}')
        return 0

    paths = args.paths or list(DEFAULT_PATHS)
    result = run(paths, args.baseline_file, root=REPO_ROOT)

    if args.baseline:
        entries = analysis.write_baseline(args.baseline_file,
                                          result['findings'])
        print(f'wrote {len(entries)} baseline entries to '
              f'{os.path.relpath(args.baseline_file, REPO_ROOT)}')
        # violations still fail: --baseline records suppressions, it
        # does not absolve unsuppressed findings
        _report({**result, 'unlisted': [], 'stale': []})
        return 1 if (result['violations'] or result['errors']) else 0

    # with --json -, stdout is RESERVED for the payload (pipeable into
    # `obs_report --archlint -`); the human report moves to stderr
    human = sys.stderr if args.json == '-' else sys.stdout
    if args.json:
        payload = json.dumps(as_json(result), indent=1, sort_keys=True)
        if args.json == '-':
            print(payload)
        else:
            with open(args.json, 'w', encoding='utf-8') as fh:
                fh.write(payload + '\n')

    _report(result, out=human)
    bad = bool(result['violations'] or result['unlisted'] or
               result['stale'] or result['errors'])
    n_v, n_s = len(result['violations']), len(result['suppressed'])
    print(f'archlint: {len(result["files"])} files, {n_v} violations, '
          f'{n_s} suppressed ({len(result["unlisted"])} unlisted, '
          f'{len(result["stale"])} stale baseline entries)', file=human)
    if args.check:
        return 1 if bad else 0
    return 1 if result['violations'] or result['errors'] else 0


if __name__ == '__main__':
    sys.exit(main())
